"""The bench regression gate: metric extraction, directions, exit codes.

``scripts/bench_compare.py`` is the machine check on the BENCH_r*.json
trajectory; these tests pin what makes it trustworthy — metrics regress
in their OWN bad direction (tok/s down = bad, ms/step up = bad), metrics
present in only one round never fail the gate, and the exit codes are
the contract CI scripts on.
"""

import importlib.util
import json
import pathlib
import sys

import pytest

_SPEC = importlib.util.spec_from_file_location(
    "bench_compare",
    pathlib.Path(__file__).resolve().parents[1] / "scripts"
    / "bench_compare.py",
)
bench_compare = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(bench_compare)


def _doc(tail_lines, value=100.0, vs_baseline=1.05):
    return {
        "parsed": {
            "metric": "case6_attention_tflops_per_chip",
            "value": value, "vs_baseline": vs_baseline,
        },
        "tail": "\n".join(tail_lines),
    }


OLD = _doc([
    "[bench] 125M decode, bf16 (b=8): 10,000 tok/s, 0.58 ms/token-step, MBU=80.0%",
    "[bench] 125M transformer train step: 66.0 ms/step, MFU=49.0%",
    "[bench] gone-next-round: 5.0 ms/step",
])


class TestExtraction:
    def test_metrics_and_directions(self):
        m = bench_compare.extract_metrics(OLD)
        assert m["headline:case6_attention_tflops_per_chip"] == (100.0, True)
        assert m["headline:vs_baseline"] == (1.05, True)
        assert m["125M_decode,_bf16_(b=8):tok_s"] == (10000.0, True)
        assert m["125M_decode,_bf16_(b=8):ms_per_token"] == (0.58, False)
        assert m["125M_decode,_bf16_(b=8):mbu_pct"] == (80.0, True)
        assert m["125M_transformer_train_step:ms_per_step"] == (66.0, False)
        assert m["125M_transformer_train_step:mfu_pct"] == (49.0, True)

    def test_activated_mfu_does_not_shadow_mfu(self):
        m = bench_compare.extract_metrics(
            _doc(["[bench] moe step: 70.0 ms/step, activated-MFU=33.0%"])
        )
        assert m["moe_step:act_mfu_pct"] == (33.0, True)
        assert "moe_step:mfu_pct" not in m

    def test_serving_latency_gates_direction_aware(self):
        """The round-9 serving gates: ITL p99, queue wait p50, refill
        share, and decode-stall share all regress when they RISE."""
        line = (
            "[bench] 125M serving latency (16 staggered arrivals, "
            "20 req/s): TTFT p50 220 ms / p99 410 ms, TPOT p50 5.4 ms, "
            "ITL p99 80 ms, queue wait p50 190 ms, 310 tok/s, refill "
            "41% of engine time, decode stalled 0%"
        )
        m = bench_compare.extract_metrics(_doc([line]))
        name = "125M_serving_latency_(16_staggered_arrivals,_20_req/s)"
        assert m[f"{name}:itl_p99_ms"] == (80.0, False)
        assert m[f"{name}:queue_wait_p50_ms"] == (190.0, False)
        assert m[f"{name}:refill_share_pct"] == (41.0, False)
        assert m[f"{name}:decode_stall_share_pct"] == (0.0, False)
        assert m[f"{name}:tok_s"] == (310.0, True)
        # The generic p99 pattern still reads TTFT's p99 (first match),
        # not ITL's — the ITL gate is its own key.
        assert m[f"{name}:p99_ms"] == (410.0, False)
        worse = _doc([line.replace("ITL p99 80 ms", "ITL p99 180 ms")
                     .replace("refill 41%", "refill 88%")])
        rows, _, _ = bench_compare.compare(_doc([line]), worse, 0.10)
        by = {r["metric"]: r for r in rows}
        assert by[f"{name}:itl_p99_ms"]["regressed"]
        assert by[f"{name}:refill_share_pct"]["regressed"]
        assert not by[f"{name}:queue_wait_p50_ms"]["regressed"]

    def test_recovery_gates_direction_aware(self):
        """The round-10 recovery gates: shed rate and deadline-miss rate
        regress when they RISE — a robustness hook that starts shedding
        clean traffic fails the round like any latency regression."""
        line = (
            "[bench] 125M serving latency (16 staggered arrivals, "
            "20 req/s): TTFT p50 220 ms / p99 410 ms, TPOT p50 5.4 ms, "
            "ITL p99 80 ms, queue wait p50 190 ms, 310 tok/s, "
            "shed 0%, deadline miss 0%"
        )
        m = bench_compare.extract_metrics(_doc([line]))
        name = "125M_serving_latency_(16_staggered_arrivals,_20_req/s)"
        assert m[f"{name}:shed_rate_pct"] == (0.0, False)
        assert m[f"{name}:deadline_miss_pct"] == (0.0, False)
        worse = _doc([
            line.replace("shed 0%", "shed 12%")
            .replace("deadline miss 0%", "deadline miss 9%")
        ])
        rows, _, _ = bench_compare.compare(_doc([line]), worse, 0.10)
        by = {r["metric"]: r for r in rows}
        assert by[f"{name}:shed_rate_pct"]["regressed"]
        assert by[f"{name}:deadline_miss_pct"]["regressed"]

    def test_fleet_gates_direction_aware(self):
        """The round-11 fleet gates: aggregate tok/s regresses DOWN,
        router-side e2e p99 regresses UP — per replica-count line, so a
        scaling regression at K=4 can't hide behind a healthy K=1."""
        lines = [
            "[bench] fleet serving K=2 (unified, (1,2) sub-meshes): "
            "aggregate 1,240 tok/s, e2e p50 310 ms, e2e p99 820 ms",
            "[bench] fleet serving K=4 (unified, (1,2) sub-meshes): "
            "aggregate 2,105 tok/s, e2e p50 300 ms, e2e p99 790 ms",
        ]
        m = bench_compare.extract_metrics(_doc(lines))
        k2 = "fleet_serving_K=2_(unified,_(1,2)_sub-meshes)"
        k4 = "fleet_serving_K=4_(unified,_(1,2)_sub-meshes)"
        assert m[f"{k2}:aggregate_tok_s"] == (1240.0, True)
        assert m[f"{k2}:e2e_p99_ms"] == (820.0, False)
        assert m[f"{k4}:aggregate_tok_s"] == (2105.0, True)
        worse = _doc([
            lines[0],
            lines[1]
            .replace("aggregate 2,105 tok/s", "aggregate 1,400 tok/s")
            .replace("e2e p99 790 ms", "e2e p99 1,900 ms"),
        ])
        rows, _, _ = bench_compare.compare(_doc(lines), worse, 0.10)
        by = {r["metric"]: r for r in rows}
        assert by[f"{k4}:aggregate_tok_s"]["regressed"]
        assert by[f"{k4}:e2e_p99_ms"]["regressed"]
        assert not by[f"{k2}:aggregate_tok_s"]["regressed"]

    def test_tenancy_swap_gates_direction_aware(self):
        """The round-12 hot-swap gate: the stall p99 (the serve gap the
        drain-mode commit costs) regresses UP; rollout throughput (the
        line's first tok/s) regresses DOWN."""
        line = (
            "[bench] 125M hot-swap under load: swap stall p50 12 ms, "
            "swap stall p99 45 ms (5 swaps, 2,900 tok/s during rollout "
            "vs 3,100 tok/s undisturbed)"
        )
        m = bench_compare.extract_metrics(_doc([line]))
        name = "125M_hot-swap_under_load"
        assert m[f"{name}:swap_stall_p99_ms"] == (45.0, False)
        assert m[f"{name}:tok_s"] == (2900.0, True)
        worse = _doc([
            line.replace("swap stall p99 45 ms", "swap stall p99 450 ms")
        ])
        rows, _, _ = bench_compare.compare(_doc([line]), worse, 0.10)
        by = {r["metric"]: r for r in rows}
        assert by[f"{name}:swap_stall_p99_ms"]["regressed"]
        assert not by[f"{name}:tok_s"]["regressed"]

    def test_tenancy_adapter_gates_direction_aware(self):
        """The round-12 multi-LoRA gates, per adapter-count line: mixed
        tok/s, solo tok/s, and the mixed/solo ratio all regress DOWN —
        the ratio falling means the per-row adapter gather got more
        expensive relative to merge_lora-folded weights."""
        lines = [
            "[bench] tenancy multi-LoRA A=4 (one fused batch, 8-dev "
            "emulated): mixed 230 tok/s, solo 6,900 tok/s, 0.03x solo "
            "(16 requests, rank 4)",
            "[bench] tenancy multi-LoRA A=16 (one fused batch, 8-dev "
            "emulated): mixed 220 tok/s, solo 1,900 tok/s, 0.12x solo "
            "(16 requests, rank 4)",
        ]
        m = bench_compare.extract_metrics(_doc(lines))
        a4 = "tenancy_multi-LoRA_A=4_(one_fused_batch,_8-dev_emulated)"
        a16 = "tenancy_multi-LoRA_A=16_(one_fused_batch,_8-dev_emulated)"
        assert m[f"{a4}:mixed_tok_s"] == (230.0, True)
        assert m[f"{a4}:solo_tok_s"] == (6900.0, True)
        assert m[f"{a4}:vs_solo_ratio"] == (0.03, True)
        assert m[f"{a16}:vs_solo_ratio"] == (0.12, True)
        worse = _doc([
            lines[0].replace("mixed 230 tok/s", "mixed 110 tok/s")
            .replace("0.03x solo", "0.01x solo"),
            lines[1],
        ])
        rows, _, _ = bench_compare.compare(_doc(lines), worse, 0.10)
        by = {r["metric"]: r for r in rows}
        assert by[f"{a4}:mixed_tok_s"]["regressed"]
        assert by[f"{a4}:vs_solo_ratio"]["regressed"]
        assert not by[f"{a16}:mixed_tok_s"]["regressed"]
        assert not by[f"{a4}:solo_tok_s"]["regressed"]

    def test_goodput_gates_direction_aware(self):
        """The round-14 ledger gates: host_share (the fraction of busy
        wall spent OFF the device — the number ROADMAP item 1 pushes
        down) and the telemetry self-overhead regress UP; goodput_ratio
        regresses DOWN; the trace-derived TTFT critical-path p50 and p99
        tails regress UP like every latency metric."""
        line = (
            "[bench] goodput: host_share 82.0%, goodput_ratio 6.25%, "
            "top contributor sched (1.20 s of 5.00 s), telemetry "
            "overhead 0.45%, TTFT critical path p50 220 ms / p99 410 "
            "ms, reconcile ok (residual 0.12 ms)"
        )
        m = bench_compare.extract_metrics(_doc([line]))
        assert m["goodput:host_share_pct"] == (82.0, False)
        assert m["goodput:goodput_ratio_pct"] == (6.25, True)
        assert m["goodput:telemetry_overhead_pct"] == (0.45, False)
        assert m["goodput:ttft_cp_p50_ms"] == (220.0, False)
        # The generic `p99 X ms` pattern picks up the tail too.
        assert m["goodput:p99_ms"] == (410.0, False)
        worse = _doc([
            line.replace("host_share 82.0%", "host_share 99.1%")
            .replace("goodput_ratio 6.25%", "goodput_ratio 3.00%")
            .replace("telemetry overhead 0.45%", "telemetry overhead 1.90%")
        ])
        rows, _, _ = bench_compare.compare(_doc([line]), worse, 0.10)
        by = {r["metric"]: r for r in rows}
        assert by["goodput:host_share_pct"]["regressed"]
        assert by["goodput:goodput_ratio_pct"]["regressed"]
        assert by["goodput:telemetry_overhead_pct"]["regressed"]
        assert not by["goodput:ttft_cp_p50_ms"]["regressed"]
        assert not by["goodput:p99_ms"]["regressed"]

    def test_kv_economy_gates_direction_aware(self):
        """The round-15 KV-economy gates, per A/B line: aggregate tok/s
        and the prefix-hit rate regress DOWN; fleet TTFT p99, the
        tier-miss rate, and kv bytes moved per request regress UP —
        the aware and blind lines gate independently, so the economy
        regressing can't hide behind a healthy blind baseline."""
        lines = [
            "[bench] kv economy K=4 prefix-aware (80% overlap): "
            "aggregate 1,115 tok/s, TTFT p99 315.6 ms, prefix hit 77%, "
            "tier miss 4%, kv moved 7.7 kB/req (spill 369 kB, fill 0 "
            "kB, peer 0 pages)",
            "[bench] kv economy K=4 prefix-blind (80% overlap): "
            "aggregate 883 tok/s, TTFT p99 381.7 ms",
        ]
        m = bench_compare.extract_metrics(_doc(lines))
        aware = "kv_economy_K=4_prefix-aware_(80%_overlap)"
        blind = "kv_economy_K=4_prefix-blind_(80%_overlap)"
        assert m[f"{aware}:aggregate_tok_s"] == (1115.0, True)
        assert m[f"{aware}:ttft_p99_ms"] == (315.6, False)
        assert m[f"{aware}:prefix_hit_rate_pct"] == (77.0, True)
        assert m[f"{aware}:tier_miss_rate_pct"] == (4.0, False)
        assert m[f"{aware}:kv_bytes_moved_per_req_kb"] == (7.7, False)
        assert m[f"{blind}:aggregate_tok_s"] == (883.0, True)
        assert m[f"{blind}:ttft_p99_ms"] == (381.7, False)
        worse = _doc([
            lines[0]
            .replace("prefix hit 77%", "prefix hit 31%")
            .replace("tier miss 4%", "tier miss 38%")
            .replace("kv moved 7.7 kB/req", "kv moved 64.0 kB/req")
            .replace("TTFT p99 315.6 ms", "TTFT p99 612.0 ms"),
            lines[1],
        ])
        rows, _, _ = bench_compare.compare(_doc(lines), worse, 0.10)
        by = {r["metric"]: r for r in rows}
        assert by[f"{aware}:prefix_hit_rate_pct"]["regressed"]
        assert by[f"{aware}:tier_miss_rate_pct"]["regressed"]
        assert by[f"{aware}:kv_bytes_moved_per_req_kb"]["regressed"]
        assert by[f"{aware}:ttft_p99_ms"]["regressed"]
        assert not by[f"{aware}:aggregate_tok_s"]["regressed"]
        assert not by[f"{blind}:ttft_p99_ms"]["regressed"]

    def test_multistep_gates_direction_aware(self):
        """The round-16 multi-step gates, per horizon rung:
        steps/dispatch (engine iterations fused per host round-trip —
        the number the device-resident scheduler pushes up) regresses
        DOWN; host_share and boundary stall regress UP; tok/s rides the
        generic pattern; ITL p99 rides the generic `p99 X ms` latency
        pattern. Each rung line gates independently, so a deep-horizon
        rung silently falling back to one-step dispatches (steps/
        dispatch -> 1.0) fails the gate even if tok/s holds."""
        lines = [
            "[bench] multistep h1: 568 tok/s, host_share 85.9%, "
            "steps/dispatch 1.00, ITL p99 16.7 ms, boundary stall 10.4%",
            "[bench] multistep h16: 1,213 tok/s, host_share 42.2%, "
            "steps/dispatch 15.59, ITL p99 42.0 ms, boundary stall 6.9%",
        ]
        m = bench_compare.extract_metrics(_doc(lines))
        assert m["multistep_h1:steps_per_dispatch"] == (1.0, True)
        assert m["multistep_h16:steps_per_dispatch"] == (15.59, True)
        assert m["multistep_h16:host_share_pct"] == (42.2, False)
        assert m["multistep_h16:boundary_stall_pct"] == (6.9, False)
        assert m["multistep_h16:tok_s"] == (1213.0, True)
        assert m["multistep_h16:p99_ms"] == (42.0, False)
        worse = _doc([
            lines[0],
            lines[1]
            .replace("steps/dispatch 15.59", "steps/dispatch 1.00")
            .replace("host_share 42.2%", "host_share 83.0%")
            .replace("boundary stall 6.9%", "boundary stall 39.0%"),
        ])
        rows, _, _ = bench_compare.compare(_doc(lines), worse, 0.10)
        by = {r["metric"]: r for r in rows}
        assert by["multistep_h16:steps_per_dispatch"]["regressed"]
        assert by["multistep_h16:host_share_pct"]["regressed"]
        assert by["multistep_h16:boundary_stall_pct"]["regressed"]
        assert not by["multistep_h16:tok_s"]["regressed"]
        assert not by["multistep_h1:steps_per_dispatch"]["regressed"]

    def test_layout_search_gates_direction_aware(self):
        """The round-17 layout-search gates: the gap between the
        hand-tuned layout and the searched argmin regresses UP (a
        growing gap means the hand layouts drifted from optimal), and
        so does the predicted-vs-measured error of the cost model on
        the two compiled layouts. `layout err` must NOT ride the
        round-8 `model err` pattern — they gate different things."""
        lines = [
            "[bench] layout_search train_step (2x4 emulated, budget 48): "
            "searched 48 candidates (31 pruned) in 1.7s, 2 leaves moved, "
            "layout gap 32.5% (TPU v5 lite)",
            "[bench] layout_search train_step measured: hand 15.10 vs "
            "argmin 13.88 ms measured (delta +8.1%), layout err 19.6% "
            "(hand 19.6%, argmin 12.4%, cpu-x8)",
        ]
        m = bench_compare.extract_metrics(_doc(lines))
        assert m[
            "layout_search_train_step_(2x4_emulated,_budget_48)"
            ":layout_search_gap_pct"
        ] == (32.5, False)
        assert m["layout_search_train_step_measured"
                 ":layout_predicted_vs_measured_pct"] == (19.6, False)
        assert not any(
            k.endswith(":predicted_vs_measured_pct") for k in m
        )
        worse = _doc([
            lines[0].replace("layout gap 32.5%", "layout gap 55.0%"),
            lines[1].replace("layout err 19.6% ", "layout err 41.0% "),
        ])
        rows, _, _ = bench_compare.compare(_doc(lines), worse, 0.10)
        by = {r["metric"]: r for r in rows}
        assert by[
            "layout_search_train_step_(2x4_emulated,_budget_48)"
            ":layout_search_gap_pct"
        ]["regressed"]
        assert by["layout_search_train_step_measured"
                  ":layout_predicted_vs_measured_pct"]["regressed"]

    def test_memflow_gates_direction_aware(self):
        """The round-18 memflow gates: the static liveness analyzer's
        predicted-vs-measured peak-HBM error per searchable entry (and
        the summary's worst-of line) regresses UP — the error growing
        means the donation/scan/sharding model drifted from what XLA
        allocates, which bounds the OOM gate's accuracy. `memflow err`
        must not ride shardflow's `model err` or the search's `layout
        err` patterns."""
        lines = [
            "[bench] memflow train_step: predicted peak 101.6 "
            "MiB/device at train_step:dot_general pipeline.py:88, "
            "XLA measures 54.9 MiB, memflow err 85.2%",
            "[bench] memflow summary: worst of 4 entries, "
            "memflow err 85.2%",
        ]
        m = bench_compare.extract_metrics(_doc(lines))
        assert m["memflow_train_step"
                 ":memflow_predicted_vs_measured_pct"] == (85.2, False)
        assert m["memflow_summary"
                 ":memflow_predicted_vs_measured_pct"] == (85.2, False)
        assert not any(
            k.endswith(":predicted_vs_measured_pct")
            or k.endswith(":layout_predicted_vs_measured_pct")
            for k in m
        )
        worse = _doc([
            lines[0].replace("memflow err 85.2%", "memflow err 120.0%"),
            lines[1],
        ])
        rows, _, _ = bench_compare.compare(_doc(lines), worse, 0.10)
        by = {r["metric"]: r for r in rows}
        assert by["memflow_train_step"
                  ":memflow_predicted_vs_measured_pct"]["regressed"]
        assert not by["memflow_summary"
                      ":memflow_predicted_vs_measured_pct"]["regressed"]

    def test_commscope_gates_direction_aware(self):
        """The round-19 commscope gates: per-axis measured bandwidth
        regresses DOWN (higher is better), while the fit error, the
        exposed-comm share, and the calibrated prediction error all
        regress UP. `comm prediction err` must not ride shardflow's
        `model err` pattern, and the overlap ratio on the same line is
        deliberately ungated (a scheduling outcome, not monotonic)."""
        lines = [
            "[bench] commscope axis data (8-dev emulated): "
            "axis bandwidth 0.290 GB/s, alpha 1440.5 us, "
            "comm fit err 128.4%",
            "[bench] commscope overlap (8-dev emulated): "
            "exposed comm 58.65% of device, overlap ratio 11.6%, "
            "comm prediction err 423.1%",
        ]
        m = bench_compare.extract_metrics(_doc(lines))
        assert m["commscope_axis_data_(8-dev_emulated)"
                 ":comm_axis_bandwidth_gb_s"] == (0.290, True)
        assert m["commscope_axis_data_(8-dev_emulated)"
                 ":comm_fit_err_pct"] == (128.4, False)
        assert m["commscope_overlap_(8-dev_emulated)"
                 ":exposed_comm_share_pct"] == (58.65, False)
        assert m["commscope_overlap_(8-dev_emulated)"
                 ":comm_model_err_pct"] == (423.1, False)
        # the overlap ratio stays ungated, and the prediction error
        # never double-matches the shardflow `model err` gate
        assert not any("overlap_ratio" in k for k in m)
        assert not any(
            k.endswith(":predicted_vs_measured_pct") for k in m
        )
        worse = _doc([
            lines[0].replace("axis bandwidth 0.290", "axis bandwidth 0.100")
                    .replace("comm fit err 128.4%", "comm fit err 200.0%"),
            lines[1].replace("exposed comm 58.65%", "exposed comm 80.00%")
                    .replace("comm prediction err 423.1%",
                             "comm prediction err 900.0%"),
        ])
        rows, _, _ = bench_compare.compare(_doc(lines), worse, 0.10)
        by = {r["metric"]: r for r in rows}
        assert by["commscope_axis_data_(8-dev_emulated)"
                  ":comm_axis_bandwidth_gb_s"]["regressed"]
        assert by["commscope_axis_data_(8-dev_emulated)"
                  ":comm_fit_err_pct"]["regressed"]
        assert by["commscope_overlap_(8-dev_emulated)"
                  ":exposed_comm_share_pct"]["regressed"]
        assert by["commscope_overlap_(8-dev_emulated)"
                  ":comm_model_err_pct"]["regressed"]
        better = _doc([
            lines[0].replace("axis bandwidth 0.290", "axis bandwidth 0.500"),
            lines[1].replace("exposed comm 58.65%", "exposed comm 20.00%"),
        ])
        rows, _, _ = bench_compare.compare(_doc(lines), better, 0.10)
        by = {r["metric"]: r for r in rows}
        assert not by["commscope_axis_data_(8-dev_emulated)"
                      ":comm_axis_bandwidth_gb_s"]["regressed"]
        assert not by["commscope_overlap_(8-dev_emulated)"
                      ":exposed_comm_share_pct"]["regressed"]

    def test_economics_gates_direction_aware(self):
        """The round-20 workload-observatory gates: cost per generated
        token and the worst tenant's SLO burn rate regress UP; the
        goodput ratio rides the round-14 pattern and regresses DOWN.
        Burn holds at exactly 0.00 on a clean round, so the zero-old
        1-unit floor is what makes a 0 → 1.5 burn jump FAIL the gate
        instead of sailing through a div-by-zero pass."""
        line = (
            "[bench] economics replay K=4 (canonical day, speed 2x): "
            "goodput_ratio 1.1%, cost/token 12.291 u$, worst tenant "
            "burn 0.00 (interactive), 79 requests (0 shed), 1264 tok"
        )
        m = bench_compare.extract_metrics(_doc([line]))
        name = "economics_replay_K=4_(canonical_day,_speed_2x)"
        assert m[f"{name}:cost_per_token_uusd"] == (12.291, False)
        assert m[f"{name}:worst_tenant_burn_rate"] == (0.0, False)
        assert m[f"{name}:goodput_ratio_pct"] == (1.1, True)
        worse = _doc([
            line.replace("cost/token 12.291 u$", "cost/token 30.000 u$")
            .replace("worst tenant burn 0.00", "worst tenant burn 1.50")
            .replace("goodput_ratio 1.1%", "goodput_ratio 0.4%")
        ])
        rows, _, _ = bench_compare.compare(_doc([line]), worse, 0.10)
        by = {r["metric"]: r for r in rows}
        assert by[f"{name}:cost_per_token_uusd"]["regressed"]
        assert by[f"{name}:worst_tenant_burn_rate"]["regressed"]
        assert by[f"{name}:goodput_ratio_pct"]["regressed"]
        better = _doc([
            line.replace("cost/token 12.291 u$", "cost/token 6.000 u$")
            .replace("goodput_ratio 1.1%", "goodput_ratio 2.5%")
        ])
        rows, _, _ = bench_compare.compare(_doc([line]), better, 0.10)
        by = {r["metric"]: r for r in rows}
        assert not by[f"{name}:cost_per_token_uusd"]["regressed"]
        assert not by[f"{name}:worst_tenant_burn_rate"]["regressed"]
        assert not by[f"{name}:goodput_ratio_pct"]["regressed"]

    def test_topology_gates_direction_aware(self):
        """The round-21 topology gates: the overlap-aware reconcile
        error per entry, the train step's priced DCN bytes/token, and
        the profile-vs-ledger overlap gap all regress UP; the seeded
        flat-vs-topo argmin canary is the one HIGHER-is-better analyzer
        gate — deterministic abstract pricing, so it only moves when
        hierarchy pricing loses its discrimination power. `topo err`
        must not ride `model err` / `layout err` / `memflow err` /
        `comm prediction err`, and the serial-sum context number on the
        same line stays ungated (serial is the upper bound, not the
        claim)."""
        lines = [
            "[bench] topo train_step: measured 21.77 ms vs "
            "overlap-aware 23.50 ms, topo err 8.0% (serial-sum "
            "196.8%), dcn 983.0 kB predicted / 2670.6 kB contract",
            "[bench] topo dcn: train_step moves 320.1 dcn B/token "
            "(983040 B over 3072 tokens)",
            "[bench] topo overlap: train_step profile predicts 0.68, "
            "ledger realized 0.65, overlap gap 3.0 pp",
            "[bench] topo argmin: flat argmin moves 0.1 kB over DCN, "
            "topo argmin 0.0 kB; topo argmin gap 7304.8% (2x4 "
            "two-tier seeded, budget 96)",
            "[bench] topo summary: worst of 4 entries, topo err 56.5%",
        ]
        m = bench_compare.extract_metrics(_doc(lines))
        assert m["topo_train_step:topo_reconcile_err_pct"] == (8.0, False)
        assert m["topo_summary:topo_reconcile_err_pct"] == (56.5, False)
        assert m["topo_dcn:dcn_bytes_per_token"] == (320.1, False)
        assert m["topo_overlap"
                 ":overlap_predicted_vs_realized_pp"] == (3.0, False)
        assert m["topo_argmin:topo_argmin_gap_pct"] == (7304.8, True)
        # No cross-matching into the other four analyzer error gates,
        # and the serial-sum context number is extracted by nothing.
        assert not any(
            k.endswith(":predicted_vs_measured_pct")
            or k.endswith(":layout_predicted_vs_measured_pct")
            or k.endswith(":memflow_predicted_vs_measured_pct")
            or k.endswith(":comm_model_err_pct")
            for k in m
        )
        assert not any("196" in str(v[0]) for v in m.values())
        worse = _doc([
            lines[0].replace("topo err 8.0%", "topo err 40.0%"),
            lines[1].replace("320.1 dcn B/token", "900.0 dcn B/token"),
            lines[2].replace("overlap gap 3.0 pp", "overlap gap 25.0 pp"),
            lines[3].replace("topo argmin gap 7304.8%",
                             "topo argmin gap 0.0%"),
            lines[4],
        ])
        rows, _, _ = bench_compare.compare(_doc(lines), worse, 0.10)
        by = {r["metric"]: r for r in rows}
        assert by["topo_train_step:topo_reconcile_err_pct"]["regressed"]
        assert by["topo_dcn:dcn_bytes_per_token"]["regressed"]
        assert by["topo_overlap"
                  ":overlap_predicted_vs_realized_pp"]["regressed"]
        assert by["topo_argmin:topo_argmin_gap_pct"]["regressed"]
        assert not by["topo_summary:topo_reconcile_err_pct"]["regressed"]
        better = _doc([
            lines[0].replace("topo err 8.0%", "topo err 2.0%"),
            lines[1].replace("320.1 dcn B/token", "100.0 dcn B/token"),
            lines[2].replace("overlap gap 3.0 pp", "overlap gap 0.5 pp"),
            lines[3].replace("topo argmin gap 7304.8%",
                             "topo argmin gap 9000.0%"),
            lines[4],
        ])
        rows, _, _ = bench_compare.compare(_doc(lines), better, 0.10)
        by = {r["metric"]: r for r in rows}
        assert not by["topo_train_step:topo_reconcile_err_pct"]["regressed"]
        assert not by["topo_dcn:dcn_bytes_per_token"]["regressed"]
        assert not by["topo_overlap"
                      ":overlap_predicted_vs_realized_pp"]["regressed"]
        assert not by["topo_argmin:topo_argmin_gap_pct"]["regressed"]

    def test_compression_gates_direction_aware(self):
        """The round-22 comm-compression gates: compressed tok/s and
        q8 agreement regress DOWN (the drift oracle holds agreement at
        100%, so any slip is a numerics change); the KV wire kB/req
        regresses UP and the raw/wire compression ratio DOWN. `q8
        agreement` must not ride the speculative pass's `agreement vs
        plain:` pattern, `kv wire` must not ride round-15's pre-codec
        `kv moved`, and the raw-kB context number on the same line
        stays ungated (raw is the denominator, not the claim)."""
        lines = [
            "[bench] comm compression mixed 2x4: plain 738 tok/s, "
            "compressed 634 tok/s (q8 agreement 100%)",
            "[bench] comm compression kv K=2 (int8_delta): kv wire "
            "0.8 kB/req vs 2.7 kB/req raw, compression ratio 3.56x "
            "(8 demotions, 0 promotions)",
        ]
        m = bench_compare.extract_metrics(_doc(lines))
        tp = "comm_compression_mixed_2x4"
        kv = "comm_compression_kv_K=2_(int8_delta)"
        assert m[f"{tp}:compressed_tok_s"] == (634.0, True)
        assert m[f"{tp}:q8_agreement_pct"] == (100.0, True)
        assert m[f"{kv}:kv_wire_bytes_per_req_kb"] == (0.8, False)
        assert m[f"{kv}:comm_compression_ratio"] == (3.56, True)
        # the plain tok/s rides the generic gate; no cross-matching
        # into the speculative or round-15 byte patterns; the raw
        # context number is extracted by nothing
        assert m[f"{tp}:tok_s"] == (738.0, True)
        assert not any(
            k.endswith(":agreement_pct")
            or k.endswith(":kv_bytes_moved_per_req_kb")
            for k in m
        )
        assert not any(v[0] == 2.7 for v in m.values())
        worse = _doc([
            lines[0].replace("compressed 634 tok/s", "compressed 500 tok/s")
            .replace("q8 agreement 100%", "q8 agreement 80%"),
            lines[1].replace("kv wire 0.8 kB/req", "kv wire 2.6 kB/req")
            .replace("compression ratio 3.56x", "compression ratio 1.04x"),
        ])
        rows, _, _ = bench_compare.compare(_doc(lines), worse, 0.10)
        by = {r["metric"]: r for r in rows}
        assert by[f"{tp}:compressed_tok_s"]["regressed"]
        assert by[f"{tp}:q8_agreement_pct"]["regressed"]
        assert by[f"{kv}:kv_wire_bytes_per_req_kb"]["regressed"]
        assert by[f"{kv}:comm_compression_ratio"]["regressed"]
        better = _doc([
            lines[0].replace("compressed 634 tok/s", "compressed 900 tok/s"),
            lines[1].replace("kv wire 0.8 kB/req", "kv wire 0.5 kB/req")
            .replace("compression ratio 3.56x", "compression ratio 5.00x"),
        ])
        rows, _, _ = bench_compare.compare(_doc(lines), better, 0.10)
        by = {r["metric"]: r for r in rows}
        assert not by[f"{tp}:compressed_tok_s"]["regressed"]
        assert not by[f"{tp}:q8_agreement_pct"]["regressed"]
        assert not by[f"{kv}:kv_wire_bytes_per_req_kb"]["regressed"]
        assert not by[f"{kv}:comm_compression_ratio"]["regressed"]

    def test_autoscaler_gates_direction_aware(self):
        """The round-23 elastic-fleet gates: autoscaled cost per token,
        scale-in drain p99, and the planner-vs-live gap all regress UP.
        No cross-matching: `elastic N uusd/tok` must not ride round-20's
        `cost/token N u$` serving-cost gate (and vice versa — the
        economics line must not produce an autoscale cost metric), the
        `static N uusd/tok` context number on the same line is extracted
        by nothing, `peak burn` must not ride `worst tenant burn`, and
        `planner gap` must not collide with the layout/overlap/argmin
        gap gates."""
        line = (
            "[bench] autoscale replay K<=4 (canonical day, speed 2x): "
            "elastic 9.787 uusd/tok vs static 12.251 uusd/tok "
            "(best K=2), drain p99 0.53 ms, planner gap 6.6%, peak "
            "burn 0.00 (interactive), 79 requests (0 shed), 1264 tok, "
            "decisions 12"
        )
        m = bench_compare.extract_metrics(_doc([line]))
        name = "autoscale_replay_K<=4_(canonical_day,_speed_2x)"
        assert m[f"{name}:autoscale_cost_per_token_uusd"] == (9.787, False)
        assert m[f"{name}:scale_in_drain_ms_p99"] == (0.53, False)
        assert m[f"{name}:planner_vs_live_gap_pct"] == (6.6, False)
        assert not any(
            k.endswith(":cost_per_token_uusd")
            or k.endswith(":worst_tenant_burn_rate")
            or k.endswith(":layout_search_gap_pct")
            or k.endswith(":overlap_predicted_vs_realized_pp")
            or k.endswith(":topo_argmin_gap_pct")
            for k in m
        )
        assert not any(v[0] == 12.251 for v in m.values())
        econ = (
            "[bench] economics replay K=4 (canonical day, speed 2x): "
            "goodput_ratio 1.1%, cost/token 12.291 u$, worst tenant "
            "burn 0.00 (interactive), 79 requests (0 shed), 1264 tok"
        )
        assert not any(
            k.endswith(":autoscale_cost_per_token_uusd")
            or k.endswith(":scale_in_drain_ms_p99")
            or k.endswith(":planner_vs_live_gap_pct")
            for k in bench_compare.extract_metrics(_doc([econ]))
        )
        worse = _doc([
            line.replace("elastic 9.787 uusd/tok", "elastic 14.000 uusd/tok")
            .replace("drain p99 0.53 ms", "drain p99 4.20 ms")
            .replace("planner gap 6.6%", "planner gap 31.0%")
        ])
        rows, _, _ = bench_compare.compare(_doc([line]), worse, 0.10)
        by = {r["metric"]: r for r in rows}
        assert by[f"{name}:autoscale_cost_per_token_uusd"]["regressed"]
        assert by[f"{name}:scale_in_drain_ms_p99"]["regressed"]
        assert by[f"{name}:planner_vs_live_gap_pct"]["regressed"]
        better = _doc([
            line.replace("elastic 9.787 uusd/tok", "elastic 7.000 uusd/tok")
            .replace("drain p99 0.53 ms", "drain p99 0.30 ms")
            .replace("planner gap 6.6%", "planner gap 2.0%")
        ])
        rows, _, _ = bench_compare.compare(_doc([line]), better, 0.10)
        by = {r["metric"]: r for r in rows}
        assert not by[f"{name}:autoscale_cost_per_token_uusd"]["regressed"]
        assert not by[f"{name}:scale_in_drain_ms_p99"]["regressed"]
        assert not by[f"{name}:planner_vs_live_gap_pct"]["regressed"]


class TestCompare:
    def test_regressions_follow_direction(self):
        new = _doc(
            [
                # tok/s fell 20% (bad), ms/token fell (good), MBU up (good)
                "[bench] 125M decode, bf16 (b=8): 8,000 tok/s, 0.40 ms/token-step, MBU=85.0%",
                # ms/step rose 30% (bad)
                "[bench] 125M transformer train step: 86.0 ms/step, MFU=49.0%",
                "[bench] brand-new-line: 1.0 ms/step",
            ],
            value=101.0,
        )
        rows, added, removed = bench_compare.compare(OLD, new, 0.10)
        by = {r["metric"]: r for r in rows}
        assert by["125M_decode,_bf16_(b=8):tok_s"]["regressed"]
        assert not by["125M_decode,_bf16_(b=8):ms_per_token"]["regressed"]
        assert not by["125M_decode,_bf16_(b=8):mbu_pct"]["regressed"]
        assert by["125M_transformer_train_step:ms_per_step"]["regressed"]
        assert not by["headline:case6_attention_tflops_per_chip"]["regressed"]
        assert "brand-new-line:ms_per_step" in added
        assert "gone-next-round:ms_per_step" in removed

    def test_within_threshold_is_clean(self):
        new = _doc(
            ["[bench] 125M decode, bf16 (b=8): 9,500 tok/s, "
             "0.60 ms/token-step, MBU=79.0%",
             "[bench] 125M transformer train step: 68.0 ms/step, MFU=48.0%"],
            value=99.0,
        )
        rows, _, _ = bench_compare.compare(OLD, new, 0.10)
        assert not any(r["regressed"] for r in rows)


class TestMain:
    def _write(self, tmp_path, n, doc):
        p = tmp_path / f"BENCH_r{n:02d}.json"
        p.write_text(json.dumps(doc))
        return p

    def test_exit_codes(self, tmp_path, capsys):
        self._write(tmp_path, 1, OLD)
        self._write(
            tmp_path, 2,
            _doc(["[bench] 125M decode, bf16 (b=8): 9,900 tok/s"]),
        )
        assert bench_compare.main(["--repo", str(tmp_path)]) == 0
        # A regressed round: tok/s down 50%.
        self._write(
            tmp_path, 3,
            _doc(["[bench] 125M decode, bf16 (b=8): 5,000 tok/s"]),
        )
        assert bench_compare.main(["--repo", str(tmp_path)]) == 1
        out = capsys.readouterr().out
        assert "REGRESSED" in out
        # Explicit files override discovery; loose threshold passes.
        assert bench_compare.main(
            [str(tmp_path / "BENCH_r02.json"),
             str(tmp_path / "BENCH_r03.json"), "--threshold", "0.6"]
        ) == 0

    def test_too_few_rounds(self, tmp_path):
        self._write(tmp_path, 1, OLD)
        assert bench_compare.main(["--repo", str(tmp_path)]) == 2

    def test_picks_two_most_recent_by_round(self, tmp_path, capsys):
        # r02/r10 ordering must be numeric, not lexicographic.
        self._write(tmp_path, 2, OLD)
        self._write(tmp_path, 9, OLD)
        self._write(
            tmp_path, 10,
            _doc(["[bench] 125M decode, bf16 (b=8): 5,000 tok/s"]),
        )
        assert bench_compare.main(["--repo", str(tmp_path)]) == 1
        assert "BENCH_r09.json -> BENCH_r10.json" in capsys.readouterr().out

    def test_json_output(self, tmp_path, capsys):
        self._write(tmp_path, 1, OLD)
        self._write(tmp_path, 2, OLD)
        assert bench_compare.main(["--repo", str(tmp_path), "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["regressions"] == []
        assert doc["metrics"]


def _doc_with_inventory(collectives):
    d = _doc(["[bench] 125M decode, bf16 (b=8): 10,000 tok/s"])
    d["tail"] += "\n" + json.dumps({
        "metric": "case6_attention_tflops_per_chip", "value": 100.0,
        "telemetry": {"headline_collectives": collectives},
    })
    return d


class TestCollectiveContractGate:
    """Round-8 satellite: the bench trajectory gate also holds the bench
    JSON's collective inventory to the golden shardcheck contract — comm
    drift fails like a metric regression."""

    GOLDEN = (
        pathlib.Path(__file__).resolve().parents[1]
        / "learning_jax_sharding_tpu" / "analysis" / "golden"
    )

    def test_inventory_extraction_from_tail(self):
        inv = bench_compare.extract_collective_inventory(
            _doc_with_inventory({"all-reduce": 0, "all-gather": 2})
        )
        assert inv == {"all-reduce": 0, "all-gather": 2}
        assert bench_compare.extract_collective_inventory(OLD) is None

    def test_clean_inventory_passes(self, tmp_path):
        zeros = {op: 0 for op in (
            "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
            "collective-permute",
        )}
        drift = bench_compare.check_collective_contract(
            zeros, self.GOLDEN / "bench_headline.json"
        )
        assert drift == []

    def test_inventory_drift_fails_main(self, tmp_path, capsys):
        w = TestMain()._write
        w(tmp_path, 1, OLD)
        w(tmp_path, 2, _doc_with_inventory({"all-gather": 3}))
        rc = bench_compare.main([
            "--repo", str(tmp_path), "--contracts", str(self.GOLDEN),
        ])
        assert rc == 1
        assert "collective inventory drift" in capsys.readouterr().out

    def test_missing_inventory_skips_with_note(self, tmp_path, capsys):
        w = TestMain()._write
        w(tmp_path, 1, OLD)
        w(tmp_path, 2, OLD)
        rc = bench_compare.main([
            "--repo", str(tmp_path), "--contracts", str(self.GOLDEN),
        ])
        assert rc == 0
        assert "contract check skipped" in capsys.readouterr().err

    def test_disable_with_empty_contracts(self, tmp_path):
        w = TestMain()._write
        w(tmp_path, 1, OLD)
        w(tmp_path, 2, _doc_with_inventory({"all-gather": 3}))
        assert bench_compare.main(
            ["--repo", str(tmp_path), "--contracts", ""]
        ) == 0
