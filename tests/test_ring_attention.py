"""Ring attention vs dense attention on an emulated sequence-parallel mesh.

The sequence is sharded 4-way over mesh axis 'y'; correctness requires every
query to see every key via the ppermute ring — the long-context capability the
reference lacks entirely (SURVEY.md §2.4).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learning_jax_sharding_tpu.ops.attention import causal_mask, dot_product_attention
from learning_jax_sharding_tpu.ops.ring_attention import ring_attention
from learning_jax_sharding_tpu.parallel import (
    assert_collectives,
    assert_shard_shape,
    mesh_sharding,
    put,
)

B, S, N, H = 2, 128, 2, 16


def _qkv(rng):
    return tuple(
        jnp.asarray(rng.standard_normal((B, S, N, H)).astype(np.float32))
        for _ in range(3)
    )


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, mesh24, rng, causal):
        q, k, v = _qkv(rng)
        mask = causal_mask(S) if causal else None
        expected = dot_product_attention(q, k, v, mask=mask)
        got = ring_attention(q, k, v, mesh=mesh24, axis="y", causal=causal)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(expected), rtol=2e-4, atol=2e-5
        )

    def test_output_stays_sequence_sharded(self, mesh24, rng):
        q, k, v = _qkv(rng)
        sh = mesh_sharding(mesh24, None, "y", None, None)
        q, k, v = put(q, sh), put(k, sh), put(v, sh)
        got = jax.jit(
            functools.partial(ring_attention, mesh=mesh24, axis="y", causal=True)
        )(q, k, v)
        # S=128 sharded 4-way over y → (2, 32, 2, 16) per device; the full
        # S×S score matrix never materialized.
        assert_shard_shape(got, (B, S // 4, N, H))

    def test_uses_ring_permutes(self, mesh24, rng):
        q, k, v = _qkv(rng)
        sh = mesh_sharding(mesh24, None, "y", None, None)
        q, k, v = put(q, sh), put(k, sh), put(v, sh)
        fn = functools.partial(ring_attention, mesh=mesh24, axis="y")
        assert_collectives(fn, q, k, v, require=("collective-permute",))

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_dense(self, mesh24, rng, causal):
        q, k, v = _qkv(rng)
        mask = causal_mask(S) if causal else None

        def dense_loss(q, k, v):
            return jnp.sum(jnp.square(dot_product_attention(q, k, v, mask=mask)))

        def ring_loss(q, k, v):
            out = ring_attention(q, k, v, mesh=mesh24, axis="y", causal=causal)
            return jnp.sum(jnp.square(out))

        dg = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
        rg = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
        for name, d, r in zip("qkv", dg, rg):
            np.testing.assert_allclose(
                np.asarray(r), np.asarray(d), rtol=5e-4, atol=5e-5,
                err_msg=f"d{name} mismatch",
            )
