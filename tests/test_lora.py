"""LoRA fine-tuning: frozen base, low-rank adapters, inherited shardings.

Oracles: B=0 init makes step-0 output EXACTLY the base model; training moves
the loss while the base stays bitwise frozen; adapter shardings are the
kernel's row/col specs split between A and B; merging after training equals
the runtime (base + adapter) forward.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from learning_jax_sharding_tpu.models.transformer import (
    CONFIG_TINY,
    Transformer,
    next_token_loss,
)
from learning_jax_sharding_tpu.parallel import mesh_sharding, put
from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP
from learning_jax_sharding_tpu.training.lora import (
    LoraState,
    init_lora,
    lora_shardings,
    lora_train_state,
    make_lora_train_step,
    merge_lora,
)
from learning_jax_sharding_tpu.training.pipeline import sharded_train_state


def _base(mesh, rng):
    model = Transformer(CONFIG_TINY)
    tokens = rng.integers(0, CONFIG_TINY.vocab_size, size=(8, 33)).astype(np.int32)
    sh = mesh_sharding(mesh, "data", None)
    batch = {"inputs": put(tokens[:, :-1], sh), "targets": put(tokens[:, 1:], sh)}
    state, state_sh = sharded_train_state(
        model, optax.adamw(3e-3), batch["inputs"],
        {"params": jax.random.key(0)}, mesh, RULES_DP_TP,
    )
    return model, state, state_sh, batch


class TestLoraStructure:
    def test_matches_2d_kernels_only(self, mesh22, rng):
        _, state, _, _ = _base(mesh22, rng)
        adapters = init_lora(jax.random.key(1), state.params, rank=4)
        flat = {
            tuple(getattr(k, "key", k) for k in p): v
            for p, v in jax.tree_util.tree_flatten_with_path(adapters)[0]
        }
        paths = {p[:-1] for p in flat}  # strip the lora_a/lora_b leaf key
        # Kernels adapted; embeddings/norms/biases not.
        assert ("block_0", "attn", "query", "kernel") in paths
        assert not any("tok_embed" in p or "ln_attn" in p for p in paths)
        a = adapters["block_0"]["attn"]["query"]["kernel"]["lora_a"]
        b = adapters["block_0"]["attn"]["query"]["kernel"]["lora_b"]
        assert a.shape == (64, 4) and b.shape == (4, 64)
        assert not np.any(np.asarray(b))  # B = 0: merged == base at init

    def test_merge_at_init_is_identity(self, mesh22, rng):
        model, state, _, batch = _base(mesh22, rng)
        adapters = init_lora(jax.random.key(1), state.params, rank=4)
        merged = merge_lora(state.params, adapters)
        y0 = model.apply({"params": state.params}, batch["inputs"])
        y1 = model.apply({"params": merged}, batch["inputs"])
        np.testing.assert_array_equal(np.asarray(y0), np.asarray(y1))

    def test_shardings_inherit_kernel_specs(self, mesh22, rng):
        _, state, _, _ = _base(mesh22, rng)
        adapters = init_lora(jax.random.key(1), state.params, rank=4)
        sh = lora_shardings(state.params, adapters, mesh22)
        kernel_spec = tuple(
            state.params["block_0"]["ff"]["up"]["kernel"].sharding.spec
        )
        node = sh["block_0"]["ff"]["up"]["kernel"]
        pad = kernel_spec + (None,) * (2 - len(kernel_spec))
        assert tuple(node["lora_a"].spec) == (pad[0], None)
        assert tuple(node["lora_b"].spec) == (None, pad[1])


class TestLoraTraining:
    def test_learns_with_base_frozen(self, mesh22, rng):
        model, state, state_sh, batch = _base(mesh22, rng)
        base = state.params
        base_before = jax.tree.map(np.asarray, base)
        ls = lora_train_state(
            jax.random.key(1), base, optax.adamw(1e-2), rank=8, mesh=mesh22
        )
        step = make_lora_train_step(
            model, state_sh.params,
            {k: v.sharding for k, v in batch.items()},
            mesh22, RULES_DP_TP, optax.adamw(1e-2), loss_fn=next_token_loss,
        )
        losses = []
        for _ in range(10):
            ls, loss = step(base, ls, batch)
            losses.append(float(loss))
        assert losses[-1] < losses[0], losses
        # Frozen base: bitwise unchanged by fine-tuning.
        jax.tree.map(
            lambda before, after: np.testing.assert_array_equal(
                before, np.asarray(after)
            ),
            base_before, base,
        )
        # Merged export reproduces the runtime forward: the jitted
        # merge-inside-the-program path (what the train step computes, with
        # ls.alpha) vs the eager pre-merged export (with the alpha recorded
        # in the state).
        merged = merge_lora(base, ls)
        y_runtime = jax.jit(
            lambda b, a, al, x: model.apply(
                {"params": merge_lora(b, a, alpha=al)}, x
            )
        )(base, ls.adapters, ls.alpha, batch["inputs"])
        y_merged = model.apply({"params": merged}, batch["inputs"])
        np.testing.assert_allclose(
            np.asarray(y_runtime), np.asarray(y_merged), rtol=2e-5, atol=2e-5
        )
        # And differs from the base model (training actually moved something).
        y_base = model.apply({"params": base}, batch["inputs"])
        assert np.abs(np.asarray(y_merged) - np.asarray(y_base)).max() > 1e-4

    def test_merge_uses_trained_alpha(self, mesh22, rng):
        """LoraState carries its alpha: merging via the state applies the
        trained scale, not the default."""
        _, state, _, _ = _base(mesh22, rng)
        ls = lora_train_state(
            jax.random.key(1), state.params, optax.sgd(1e-2), rank=4,
            mesh=mesh22, alpha=32.0,
        )
        # Give the adapters a nonzero delta so scale actually matters.
        ls = ls._replace(
            adapters=jax.tree.map(lambda a: a + 0.01, ls.adapters)
        )
        via_state = merge_lora(state.params, ls)
        explicit = merge_lora(state.params, ls.adapters, alpha=32.0)
        jax.tree.map(
            lambda x, y: np.testing.assert_array_equal(np.asarray(x), np.asarray(y)),
            via_state, explicit,
        )
        wrong = merge_lora(state.params, ls.adapters)  # default alpha=16
        deltas = jax.tree.map(
            lambda x, y: float(np.abs(np.asarray(x) - np.asarray(y)).max()),
            via_state, wrong,
        )
        assert max(jax.tree.leaves(deltas)) > 1e-6

    def test_adapter_count_is_small(self, mesh22, rng):
        _, state, _, _ = _base(mesh22, rng)
        adapters = init_lora(jax.random.key(1), state.params, rank=4)
        n_base = sum(x.size for x in jax.tree.leaves(state.params))
        n_lora = sum(x.size for x in jax.tree.leaves(adapters))
        assert n_lora < 0.25 * n_base

    def test_state_is_donatable_pytree(self, mesh22, rng):
        ls = LoraState(
            adapters={"k": jnp.zeros((2, 2))},
            opt_state=optax.sgd(1e-2).init({"k": jnp.zeros((2, 2))}),
            step=jnp.zeros((), jnp.int32),
            alpha=jnp.asarray(16.0),
        )
        leaves, treedef = jax.tree.flatten(ls)
        assert jax.tree.unflatten(treedef, leaves)._fields == ls._fields
