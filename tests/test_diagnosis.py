"""Telemetry stage 2 (diagnosis): flight recorder, watchdog, devview, SLO.

The pinned claims: the flight recorder's ring is bounded and its
post-mortem bundle is complete; the watchdog flags the EXACT step whose
loss/grad-norm went non-finite (through the async-probe window) and the
escalation localizes the primitive; the heartbeat flags overrun sections
from its monitor thread; devview degrades to plan-only on backends
without memory stats, flags skewed shardings by path, and attributes
collective bytes to the right mesh axis; SLO burn rates separate an
impossible target from a loose one; the multihost snapshot merge follows
the fleet rule; case19 runs end-to-end on the emulated mesh.
"""

import dataclasses
import json
import runpy
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learning_jax_sharding_tpu.telemetry import (
    FlightRecorder,
    Heartbeat,
    MetricsRegistry,
    SLOMonitor,
    SLOTarget,
    StreamingPercentile,
    Tracer,
    Watchdog,
    axis_collective_volume,
    device_memory_stats,
    localize_nan,
    memory_report,
    shard_imbalance,
)


class TestFlightRecorder:
    def test_ring_bounds_and_filter(self):
        fr = FlightRecorder(max_events=3)
        for i in range(5):
            fr.record("tick", i=i)
        fr_events = fr.events()
        assert [e["i"] for e in fr_events] == [2, 3, 4]
        assert fr.dropped == 2
        fr.record("other")
        assert [e["kind"] for e in fr.events("other")] == ["other"]
        assert all("t" in e for e in fr.events())

    def test_attached_tracer_forwards_span_closures(self):
        fr = FlightRecorder()
        tr = Tracer()
        fr.attach_tracer(tr)
        with tr.span("refill"):
            pass
        tr.instant("arrival")   # instants are NOT closures: not forwarded
        spans = fr.events("span")
        assert [e["name"] for e in spans] == ["refill"]
        assert spans[0]["dur_us"] >= 0

    def test_dump_bundle_contents(self, tmp_path):
        reg = MetricsRegistry()
        reg.counter("c").inc(3)
        tr = Tracer()
        with tr.span("s"):
            pass
        fr = FlightRecorder(registry=reg, tracer=tr)
        fr.record("engine.admit", rid=0)
        out = fr.dump(tmp_path / "bundle", error="boom")
        assert out == tmp_path / "bundle"
        events = json.loads((out / "events.json").read_text())
        assert [e["kind"] for e in events["events"]] == ["engine.admit"]
        assert json.loads((out / "registry.json").read_text())["c"] == 3
        assert json.loads((out / "trace.json").read_text())["traceEvents"]
        mem = json.loads((out / "memory.json").read_text())
        assert len(mem) == len(jax.devices())
        assert (out / "error.txt").read_text() == "boom"
        assert fr.last_dump == out
        assert fr.events("dump")   # the dump records itself

    def test_dump_is_strict_json_despite_nan_values(self, tmp_path):
        # The NaN-incident bundle is the module's whole point: a recorded
        # NaN loss (and a NaN gauge) must not make the bundle unparseable
        # by strict readers (json.dump's default emits bare NaN tokens).
        reg = MetricsRegistry()
        reg.gauge("train_loss").set(float("nan"))
        fr = FlightRecorder(registry=reg)
        fr.record("train_step", step=3, loss=float("nan"),
                  peak=float("inf"))
        out = fr.dump(tmp_path / "pm")

        def strict(path):
            def no_const(_):
                raise AssertionError(f"non-strict JSON constant in {path}")

            return json.loads(path.read_text(), parse_constant=no_const)

        ev = strict(out / "events.json")["events"][0]
        assert ev["loss"] == "NaN" and ev["peak"] == "Infinity"
        assert strict(out / "registry.json")["train_loss"] == "NaN"

    def test_capture_dumps_on_exception_and_reraises(self, tmp_path):
        fr = FlightRecorder()
        with pytest.raises(ValueError, match="kaput"):
            with fr.capture(tmp_path / "pm"):
                fr.record("work")
                raise ValueError("kaput")
        assert (tmp_path / "pm" / "events.json").exists()
        assert "ValueError" in (tmp_path / "pm" / "error.txt").read_text()
        kinds = [e["kind"] for e in fr.events()]
        assert "exception" in kinds

    def test_dump_never_overwrites_a_prior_process_bundle(
        self, tmp_path, monkeypatch
    ):
        # A fresh recorder (new process, _dump_seq=0) dumping into a
        # persistent $LJST_ARTIFACT_DIR must skip slots an earlier run
        # wrote — old forensic evidence survives.
        monkeypatch.setenv("LJST_ARTIFACT_DIR", str(tmp_path))
        (tmp_path / "postmortem1").mkdir()
        (tmp_path / "postmortem1" / "events.json").write_text("{}")
        out = FlightRecorder().dump()
        assert out == tmp_path / "postmortem2"
        assert (tmp_path / "postmortem1" / "events.json").read_text() == "{}"

    def test_artifact_dir_honors_env(self, tmp_path, monkeypatch):
        from learning_jax_sharding_tpu.telemetry import artifact_dir

        monkeypatch.setenv("LJST_ARTIFACT_DIR", str(tmp_path / "art"))
        p = artifact_dir("case99")
        assert p == tmp_path / "art" / "case99" and p.is_dir()
        monkeypatch.delenv("LJST_ARTIFACT_DIR")
        q = artifact_dir("case99")
        assert q.is_dir() and "case99" in q.name
        assert not str(q).startswith(str(tmp_path))


class TestWatchdog:
    def test_finite_run_never_trips(self):
        w = Watchdog(lag=2)
        for i in range(6):
            w.probe(i + 1, jnp.float32(1.0 + 0.01 * i), jnp.float32(0.5))
        w.flush()
        assert not w.tripped and w.steps_probed == 6

    def test_nan_loss_flags_the_step(self):
        reg = MetricsRegistry()
        fr = FlightRecorder()
        w = Watchdog(registry=reg, recorder=fr, lag=2)
        losses = [1.0, 0.9, float("nan"), 0.8]
        for i, v in enumerate(losses):
            w.probe(i + 1, jnp.float32(v))
        w.flush()
        assert w.tripped and w.first_bad_step == 3
        assert w.bad_what == "loss"
        assert reg.get("watchdog_nonfinite_total").value == 1
        assert [e["step"] for e in fr.events("nonfinite")] == [3]

    def test_inf_grad_norm_flags_grad_norm(self):
        w = Watchdog(lag=0)
        w.probe(1, jnp.float32(1.0), jnp.float32(np.inf))
        w.flush()
        assert w.tripped and w.bad_what == "grad_norm"

    def test_loss_spike_against_ema(self):
        fr = FlightRecorder()
        w = Watchdog(recorder=fr, lag=0, spike_factor=5.0, spike_min_steps=3)
        for i in range(8):
            w.probe(i + 1, jnp.float32(1.0))
        w.probe(9, jnp.float32(50.0))   # 50x the EMA
        w.flush()
        assert not w.tripped            # finite — a spike, not a NaN
        assert [s["step"] for s in w.spikes] == [9]
        assert fr.events("loss_spike")

    def test_async_window_respects_lag(self):
        w = Watchdog(lag=3)
        w.probe(1, jnp.float32(1.0))
        # is_ready on CPU turns true almost immediately; the contract is
        # weaker and is what we pin: everything drains by flush().
        w.probe(2, jnp.float32(float("nan")))
        w.flush()
        assert w.first_bad_step == 2

    def test_bind_late_attaches_sinks(self):
        # fit() late-binds its registry/recorder into an unbound
        # watchdog; constructor-given sinks must win over a later bind.
        reg, fr = MetricsRegistry(), FlightRecorder()
        w = Watchdog(lag=0)
        w.bind(registry=reg, recorder=fr)
        w.probe(1, jnp.float32(float("nan")))
        w.flush()
        assert reg.get("watchdog_nonfinite_total").value == 1
        assert fr.events("nonfinite")
        own = FlightRecorder()
        w2 = Watchdog(recorder=own)
        w2.bind(recorder=fr)
        w2.probe(1, jnp.float32(float("nan")))
        w2.flush()
        assert own.events("nonfinite") and not fr.events("nonfinite")[1:]

    def test_localize_nan_names_the_primitive(self):
        msg = localize_nan(
            lambda: jax.jit(lambda x: 0.0 * x / (1.0 - x))(jnp.float32(1.0))
        )
        assert msg is not None and "nan" in msg.lower()
        # And a finite computation localizes to nothing.
        assert localize_nan(
            lambda: jax.jit(lambda x: x * 2)(jnp.float32(1.0))
        ) is None

    def test_probe_overhead_is_bounded(self):
        # Sanity bound, not a perf claim (PERF.md carries the measured
        # number): 30 probes must cost well under 5 ms each even on the
        # slowest CI box — the probe is two eager scalar dispatches.
        w = Watchdog(lag=2)
        loss, gn = jnp.float32(1.0), jnp.float32(0.5)
        w.probe(0, loss, gn)   # warm the dispatch path
        t0 = time.perf_counter()
        for i in range(30):
            w.probe(i + 1, loss, gn)
        dt = (time.perf_counter() - t0) / 30
        w.flush()
        assert dt < 5e-3, f"watchdog probe cost {dt * 1e3:.2f} ms/step"


class TestHeartbeat:
    def test_overrun_section_is_flagged(self):
        reg = MetricsRegistry()
        fr = FlightRecorder()
        with Heartbeat(timeout=0.05, registry=reg, recorder=fr) as hb:
            with hb.expect("wedged sync"):
                time.sleep(0.25)
        assert len(hb.hangs) == 1
        assert hb.hangs[0]["label"] == "wedged sync"
        assert hb.hangs[0]["overrun"] >= 0
        assert reg.get("watchdog_hangs_total").value == 1
        assert fr.events("hang")

    def test_fast_sections_are_clean(self):
        with Heartbeat(timeout=5.0) as hb:
            for _ in range(3):
                with hb.expect("quick"):
                    pass
        assert hb.hangs == []

    def test_validation(self):
        with pytest.raises(ValueError, match="timeout"):
            Heartbeat(timeout=0.0)

    def test_running_property_tracks_thread(self):
        # fit() keys its ownership decision on this: an already-running
        # heartbeat (caller's `with hb:`) must not be stopped by fit.
        hb = Heartbeat(timeout=1.0)
        assert not hb.running
        hb.start()
        assert hb.running
        hb.stop()
        assert not hb.running


class _FakeDev:
    """Stand-in device for the memory_stats guard matrix."""

    def __init__(self, id, stats):
        self.id = id
        self.device_kind = "TPU v5 lite"
        self.platform = "tpu"
        self._stats = stats

    def memory_stats(self):
        if isinstance(self._stats, Exception):
            raise self._stats
        return self._stats


class TestDevview:
    def test_memory_stats_guard_matrix(self):
        class NoStats:
            id, device_kind, platform = 0, "cpu", "cpu"

        devs = [
            NoStats(),                                   # no attribute
            _FakeDev(1, None),                           # returns None
            _FakeDev(2, RuntimeError("unimplemented")),  # raises
            _FakeDev(3, {"bytes_in_use": 7, "weird": object()}),
        ]
        out = device_memory_stats(devs)
        assert [d["stats"] for d in out[:3]] == [{}, {}, {}]
        # Non-JSON-able values are dropped, numeric ones survive.
        assert out[3]["stats"] == {"bytes_in_use": 7}

    def test_memory_report_plan_only_on_emulated_cpu(self):
        from learning_jax_sharding_tpu.models.transformer import CONFIG_TINY
        from learning_jax_sharding_tpu.utils.memory import memory_plan

        plan = memory_plan(CONFIG_TINY, 2, 32)
        rep = memory_report(plan)
        assert rep["actual_available"] is False
        assert rep["predicted"]["total"] == plan.total
        assert "actual_peak_bytes" not in rep
        assert json.dumps(rep)   # JSON-able end to end

    def test_memory_report_predicted_vs_actual(self):
        from learning_jax_sharding_tpu.models.transformer import CONFIG_TINY
        from learning_jax_sharding_tpu.utils.memory import memory_plan

        plan = memory_plan(CONFIG_TINY, 2, 32)
        dev = _FakeDev(
            0, {"peak_bytes_in_use": int(plan.total * 2), "bytes_limit": 16_000_000_000}
        )
        rep = memory_report(plan, devices=[dev])
        assert rep["actual_available"] is True
        assert rep["actual_peak_bytes"] == int(plan.total * 2)
        assert rep["predicted_over_actual"] == pytest.approx(0.5)
        assert rep["hbm_bytes"] == 16_000_000_000
        assert rep["predicted_fits"] is True

    def test_shard_imbalance_flags_the_stray(self, mesh24):
        from jax.sharding import NamedSharding, PartitionSpec as P

        even = jax.device_put(
            np.ones((8, 16), np.float32), NamedSharding(mesh24, P("x", "y"))
        )
        stray = jax.device_put(np.ones((64, 64), np.float32), jax.devices()[0])
        rep = shard_imbalance({"even": even, "stray": stray})
        assert rep["imbalanced"] and rep["skew"] > 2.0
        assert [f["path"] for f in rep["flagged"]] == ["['stray']"]
        # Exact accounting: device 0 holds its even shard plus the stray.
        even_shard = 8 * 16 * 4 // 8
        assert rep["per_device_bytes"][0] == even_shard + 64 * 64 * 4
        assert rep["per_device_bytes"][1] == even_shard
        assert rep["total_bytes"] == 8 * 16 * 4 + 64 * 64 * 4

    def test_balanced_tree_is_clean(self, mesh24):
        from jax.sharding import NamedSharding, PartitionSpec as P

        x = jax.device_put(
            np.ones((8, 16), np.float32), NamedSharding(mesh24, P("x", "y"))
        )
        rep = shard_imbalance({"x": x})
        assert not rep["imbalanced"] and rep["skew"] == pytest.approx(1.0)
        assert rep["flagged"] == []
        # Replication is balanced too (every device holds the full array).
        r = jax.device_put(
            np.ones((4, 4), np.float32), NamedSharding(mesh24, P())
        )
        rep = shard_imbalance({"r": r})
        assert not rep["imbalanced"]
        assert rep["per_device_bytes"][0] == 4 * 4 * 4

    def test_axis_volume_attributes_the_psum_axis(self, mesh24, rng):
        from functools import partial

        from learning_jax_sharding_tpu.parallel.collectives import (
            psum_matmul,
        )
        from learning_jax_sharding_tpu.telemetry import executable_report
        from tests.conftest import matmul_operands

        a, b = matmul_operands(rng)
        rep = executable_report(
            partial(psum_matmul, mesh=mesh24, axis="y"), a, b
        )
        vol = axis_collective_volume(rep["collective_instructions"], mesh24)
        assert vol["y"]["ops"] >= 1
        assert vol["y"]["bytes"] >= 4 * 4 * 4   # the (4,4) fp32 result
        assert vol["x"] == {"ops": 0, "bytes": 0}
        assert vol["unattributed"]["ops"] == 0

    def test_axis_volume_on_crafted_hlo(self, mesh24):
        # Explicit-group, iota-group, groupless, and single-member-group
        # instructions — the parse/attribution matrix without a compile.
        hlo = "\n".join([
            "  %ar = f32[8,16]{1,0} all-reduce(%x), replica_groups={{0,1,2,3},{4,5,6,7}}, to_apply=%add",
            "  %ag = (f32[4]{0}, f32[16]{0}) all-gather-start(%y), replica_groups=[4,2]<=[2,4]T(1,0), dimensions={0}",
            "  %cp = bf16[2,2]{1,0} collective-permute(%z), source_target_pairs={{0,1}}",
            "  %deg = f32[4]{0} all-reduce(%w), replica_groups={{0},{1},{2},{3},{4},{5},{6},{7}}",
        ])
        vol = axis_collective_volume(hlo, mesh24)
        assert vol["y"] == {"ops": 1, "bytes": 8 * 16 * 4}    # explicit
        assert vol["x"] == {"ops": 1, "bytes": 16 * 4}        # iota: pairs
        assert vol["unattributed"]["ops"] == 1                # groupless cp
        # Degenerate one-member groups carry no traffic: not counted.
        total_ops = sum(v["ops"] for v in vol.values())
        assert total_ops == 3

    def test_collective_instructions_bytes_and_groups(self):
        from learning_jax_sharding_tpu.parallel.hlo import (
            collective_instructions,
        )

        hlo = "\n".join([
            "  %a = bf16[128,256]{1,0} all-reduce(%x), replica_groups={{0,1}}",
            "  %b = (s8[64]{0}, s8[512]{0}) reduce-scatter-start(%y), replica_groups=[2,4]<=[8]",
            "  %skip = f32[4]{0} all-gather-done(%b)",
            "  %c = pred[7]{0} all-to-all(%z)",
        ])
        ins = collective_instructions(hlo)
        assert [i["op"] for i in ins] == [
            "all-reduce", "reduce-scatter", "all-to-all",
        ]
        assert ins[0]["bytes"] == 128 * 256 * 2
        assert ins[0]["replica_groups"] == [[0, 1]]
        assert ins[1]["bytes"] == 512          # max tuple element
        assert ins[1]["replica_groups"] == [[0, 1, 2, 3], [4, 5, 6, 7]]
        assert ins[2]["bytes"] == 7            # pred is byte-backed
        assert ins[2]["replica_groups"] is None


class TestSLO:
    def test_streaming_percentile_windows(self):
        est = StreamingPercentile(window=4)
        for v in (1.0, 2.0, 3.0, 4.0, 100.0):
            est.observe(v)
        # 1.0 fell out of the window; count is lifetime.
        assert est.count == 5
        snap = est.snapshot()
        assert snap["window"] == 4
        assert snap["p50"] == pytest.approx(3.5)
        assert est.quantile(1.0) == 100.0
        assert StreamingPercentile().quantile(0.5) is None

    def test_target_naming_and_validation(self):
        t = SLOTarget("ttft", 0.5)
        assert t.name == "ttft_le_0.5"
        assert SLOTarget("ttft", 0.5, name="gold").name == "gold"
        with pytest.raises(ValueError, match="objective"):
            SLOTarget("ttft", 0.5, objective=1.0)

    def test_burn_rate_separates_targets(self):
        reg = MetricsRegistry()
        fr = FlightRecorder()
        mon = SLOMonitor(
            [
                SLOTarget("ttft", 0.1, objective=0.9, name="tight"),
                SLOTarget("ttft", 10.0, objective=0.9, name="loose"),
            ],
            registry=reg, recorder=fr,
        )
        for v in (0.05, 0.2, 0.3, 0.05):
            mon.observe("ttft", v)
        # tight: 2/4 bad over a 10% budget → burn 5; loose: clean.
        assert mon.burn_rate("tight") == pytest.approx(5.0)
        assert mon.burn_rate("loose") == 0.0
        assert mon.breached() == ["tight"]
        assert reg.get("slo_tight_breaches_total").value == 2
        assert reg.get("slo_tight_events_total").value == 4
        assert reg.get("slo_tight_burn_rate").value == pytest.approx(5.0)
        assert len(fr.events("slo_breach")) == 2
        snap = mon.snapshot()
        assert snap["targets"]["tight"]["healthy"] is False
        assert snap["targets"]["loose"]["healthy"] is True
        # snapshot() refreshes percentile gauges in the registry.
        assert reg.get("slo_ttft_p50") is not None
        with pytest.raises(KeyError):
            mon.burn_rate("nope")

    def test_none_observations_are_ignored(self):
        mon = SLOMonitor([SLOTarget("tpot", 1.0)])
        mon.observe("tpot", None)
        assert mon.estimator("tpot").count == 0

    def test_burn_window_evicts_old_breaches(self):
        # The running breach count must track window EVICTIONS: a burst
        # of breaches ages out of a window=4 ring once 4 clean events
        # follow — burn_rate returns to 0, not a lifetime average.
        mon = SLOMonitor(
            [SLOTarget("ttft", 1.0, objective=0.5, name="t")], window=4
        )
        for _ in range(4):
            mon.observe("ttft", 2.0)   # all bad
        assert mon.burn_rate("t") == pytest.approx(2.0)
        for _ in range(4):
            mon.observe("ttft", 0.5)   # all good: breaches evicted
        assert mon.burn_rate("t") == 0.0
        assert mon.snapshot()["targets"]["t"]["breaches"] == 4  # lifetime


class TestMultihostGather:
    def test_single_process_gather(self):
        from learning_jax_sharding_tpu.parallel.multihost import (
            allgather_registry_snapshots,
        )

        reg = MetricsRegistry()
        reg.counter("reqs_total").inc(5)
        reg.gauge("depth").set(3)
        reg.histogram("lat", buckets=(1.0,)).observe(0.5)
        out = allgather_registry_snapshots(reg)
        assert out["process_count"] == 1
        assert len(out["hosts"]) == 1
        assert out["merged"] == reg.snapshot()

    def test_merge_rule(self):
        from learning_jax_sharding_tpu.parallel.multihost import (
            merge_registry_snapshots,
        )

        h0 = {
            "reqs_total": 5, "depth": 3, "depth__high_water": 7,
            "lat": {"buckets": [1.0], "counts": [1, 2], "sum": 1.5, "count": 2},
        }
        h1 = {
            "reqs_total": 2, "depth": 1, "depth__high_water": 9,
            "lat": {"buckets": [1.0], "counts": [0, 1], "sum": 2.0, "count": 1},
            "only_h1": 4,
        }
        m = merge_registry_snapshots([h0, h1])
        assert m["reqs_total"] == 7            # counters sum
        assert m["depth"] == 4                 # gauges sum (fleet depth)
        assert m["depth__high_water"] == 9     # high-water takes max
        assert m["lat"]["counts"] == [1, 3]
        assert m["lat"]["sum"] == 3.5 and m["lat"]["count"] == 3
        assert m["only_h1"] == 4
        # The inputs are not mutated by the merge.
        assert h0["lat"]["counts"] == [1, 2]


class TestEngineDiagnosis:
    """The serving engine's stage-2 feeds: flight-recorder lifecycle
    events, the SLO monitor, per-axis volume, dump_diagnostics."""

    @pytest.fixture(scope="class")
    def served(self, mesh22):
        import flax.linen as nn

        from learning_jax_sharding_tpu.models.serving import (
            ContinuousEngine,
        )
        from learning_jax_sharding_tpu.models.transformer import (
            CONFIG_TINY, Transformer,
        )
        from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP

        cfg = dataclasses.replace(CONFIG_TINY, dtype=jnp.float32)
        rng = np.random.default_rng(7)
        model = Transformer(cfg)
        params = nn.meta.unbox(
            jax.jit(lambda r, t: model.init({"params": r}, t))(
                jax.random.key(3), np.zeros((2, 8), np.int32)
            )["params"]
        )
        prompts = [
            rng.integers(1, cfg.vocab_size, size=(n,)).astype(np.int32)
            for n in (3, 6)
        ]
        fr = FlightRecorder()
        slo = SLOMonitor(
            [SLOTarget("ttft", 1e-9, objective=0.9, name="impossible")]
        )
        eng = ContinuousEngine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=3,
            refill_chunk=4, slo=slo, recorder=fr,
        )
        outs = eng.serve(params, prompts)
        return eng, fr, slo, prompts, outs

    def test_lifecycle_events_in_ring(self, served):
        eng, fr, _, prompts, _ = served
        admits = fr.events("engine.admit")
        retires = fr.events("engine.retire")
        assert len(admits) == len(prompts)
        assert len(retires) == len(prompts)
        assert {e["rid"] for e in retires} == set(range(len(prompts)))
        assert fr.events("engine.cache_create")
        assert fr.events("engine.arrival")
        # Attached-tracer closures ride along with the lifecycle events.
        assert any(
            e["name"].startswith("engine.") for e in fr.events("span")
        )

    def test_slo_bound_to_engine_registry(self, served):
        eng, _, slo, prompts, _ = served
        assert slo.registry is eng.registry
        assert slo.estimator("ttft").count == len(prompts)
        assert slo.estimator("queue_wait").count == len(prompts)
        assert slo.burn_rate("impossible") > 1.0
        assert "slo_impossible_breaches_total" in (
            eng.registry.prometheus_text()
        )

    def test_collective_axis_volume_structure(self, served):
        eng, _, _, _, _ = served
        vol = eng.collective_axis_volume()
        assert {"decode_block", "refill_step", "first_refill"} <= set(vol)
        for program in vol.values():
            assert set(program) <= {"data", "model", "data+model",
                                    "unattributed"}
            for v in program.values():
                assert v["ops"] >= 0 and v["bytes"] >= 0

    def test_dump_diagnostics_bundle(self, served, tmp_path):
        eng, _, _, _, _ = served
        out = eng.dump_diagnostics(tmp_path / "diag")
        assert (out / "events.json").exists()
        assert (out / "registry.json").exists()
        assert (out / "trace.json").exists()
        snap = json.loads((out / "registry.json").read_text())
        assert snap["engine_requests_finished_total"] >= 2


class TestFitWatchdogIntegration:
    def test_setup_failure_leaks_no_monitor_thread(self, mesh22):
        # fit starts the CompileWatch listener and an owned heartbeat
        # thread only once setup survived: a raise while loading the
        # first batch must leave the heartbeat un-started.
        from learning_jax_sharding_tpu.models.transformer import (
            CONFIG_TINY, Transformer,
        )
        from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP
        from learning_jax_sharding_tpu.training.loop import (
            TrainLoopConfig, fit,
        )

        class BoomDataset:
            def batch(self, index, rows=None, batch_size=8):
                raise RuntimeError("boom")

        hb = Heartbeat(timeout=5.0)
        with pytest.raises(RuntimeError, match="boom"):
            fit(
                Transformer(CONFIG_TINY), BoomDataset(), mesh22,
                RULES_DP_TP,
                TrainLoopConfig(steps=1, global_batch_size=4, prefetch=0),
                heartbeat=hb,
            )
        assert not hb.running

    def test_grad_norm_step_returns_dict(self, mesh22):
        import optax

        from learning_jax_sharding_tpu.models.transformer import (
            CONFIG_TINY, Transformer, next_token_loss,
        )
        from learning_jax_sharding_tpu.parallel import mesh_sharding, put
        from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP
        from learning_jax_sharding_tpu.training.pipeline import (
            make_train_step, sharded_train_state,
        )

        cfg = dataclasses.replace(CONFIG_TINY, dtype=jnp.float32)
        rng = np.random.default_rng(0)
        tokens = rng.integers(0, cfg.vocab_size, size=(4, 17)).astype(np.int32)
        sh = mesh_sharding(mesh22, "data", None)
        batch = {
            "inputs": put(tokens[:, :-1], sh),
            "targets": put(tokens[:, 1:], sh),
        }
        state, state_sh = sharded_train_state(
            Transformer(cfg), optax.adamw(1e-3), batch["inputs"],
            {"params": jax.random.key(0)}, mesh22, RULES_DP_TP,
        )
        step = make_train_step(
            state_sh, {k: v.sharding for k, v in batch.items()}, mesh22,
            RULES_DP_TP, loss_fn=next_token_loss, with_grad_norm=True,
            donate_state=False,
        )
        _, out = step(state, batch)
        assert set(out) == {"loss", "grad_norm"}
        assert np.isfinite(float(out["loss"]))
        assert float(out["grad_norm"]) > 0


class TestCase19Smoke:
    """CI smoke for the diagnosis driver: run cases/case19_diagnosis.py
    on the emulated 8-device mesh (every PASS line asserts internally)
    and check the report artifact."""

    def test_case19_report(self, tmp_path):
        import pathlib

        repo = pathlib.Path(__file__).resolve().parents[1]
        argv = sys.argv
        path = sys.path[:]
        sys.argv = ["case19_diagnosis.py", str(tmp_path)]
        sys.path.insert(0, str(repo / "cases"))
        try:
            runpy.run_path(
                str(repo / "cases" / "case19_diagnosis.py"),
                run_name="__main__",
            )
        finally:
            sys.argv = argv
            sys.path[:] = path

        report = json.loads((tmp_path / "report.json").read_text())
        for key in (
            "induced_nan", "imbalance", "slo", "memory_report",
            "collective_axis_volume",
        ):
            assert key in report, key
        assert report["induced_nan"]["flagged_step"] == 5
        assert "nonfinite" in report["induced_nan"]["event_kinds"]
        assert report["imbalance"]["skew"] > 1.25
        assert report["slo"]["targets"]["ttft_impossible"]["burn_rate"] > 1
        assert report["memory_report"]["actual_available"] is False
        decode = report["collective_axis_volume"]["decode_block"]
        assert sum(v["bytes"] for v in decode.values()) > 0
