"""Quantized KV cache: int8 storage with per-(token, head) scales.

At serving time the KV cache — not the weights — is what caps
batch × context (`models/attention.py::MultiHeadAttention.kv_heads`); int8
storage roughly halves it vs bf16. Oracles: the quantized cache must not
change WHAT the model decodes (greedy tokens track the full-precision cache
closely; logits stay near), the cache tree must actually shrink, and the
serving decoders (generate, beam with its cache gather) must run unchanged.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from learning_jax_sharding_tpu.models.beam import make_beam_search_fn
from learning_jax_sharding_tpu.models.generate import make_generate_fn
from learning_jax_sharding_tpu.models.transformer import (
    CONFIG_TINY,
    Transformer,
    next_token_loss,
)
from learning_jax_sharding_tpu.parallel import mesh_sharding, put
from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP, activate
from learning_jax_sharding_tpu.training.pipeline import (
    make_train_step,
    sharded_train_state,
)

CFG_INT8 = dataclasses.replace(CONFIG_TINY, kv_cache_dtype=jnp.int8)


@pytest.fixture(scope="module")
def trained(mesh22):
    model = Transformer(CONFIG_TINY)
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, CONFIG_TINY.vocab_size, size=(8, 33)).astype(np.int32)
    sh = mesh_sharding(mesh22, "data", None)
    batch = {"inputs": put(tokens[:, :-1], sh), "targets": put(tokens[:, 1:], sh)}
    state, state_sh = sharded_train_state(
        model, optax.adamw(3e-3), batch["inputs"],
        {"params": jax.random.key(0)}, mesh22, RULES_DP_TP,
    )
    step = make_train_step(
        state_sh, {k: v.sharding for k, v in batch.items()}, mesh22,
        RULES_DP_TP, loss_fn=next_token_loss, donate_state=False,
    )
    for _ in range(6):
        state, _ = step(state, batch)
    return state.params, tokens


class TestInt8KVCache:
    def test_cache_tree_is_int8_with_scales_and_halves_bytes(self, mesh22, trained):
        params, tokens = trained
        prompt = jnp.asarray(tokens[:2, :8])

        def cache_of(cfg):
            model = Transformer(dataclasses.replace(cfg, decode=True))
            with activate(mesh22, RULES_DP_TP):
                _, variables = model.apply(
                    {"params": params}, prompt, mutable=("cache",)
                )
            return variables["cache"]

        cache_q = cache_of(CFG_INT8)
        leaf = cache_q["block_0"]["attn"]
        assert leaf["cached_key"].dtype == jnp.int8
        assert leaf["key_scale"].shape == leaf["cached_key"].shape[:-1]
        cache_bf = cache_of(
            dataclasses.replace(CONFIG_TINY, kv_cache_dtype=jnp.bfloat16)
        )
        nbytes = lambda tree: sum(
            x.size * x.dtype.itemsize for x in jax.tree.leaves(tree)
        )
        # int8 + fp32/head_dim scales vs bf16: close to half.
        assert nbytes(cache_q) < 0.7 * nbytes(cache_bf)

    def test_greedy_decode_tracks_full_precision(self, mesh22, trained):
        params, tokens = trained
        prompt = put(tokens[:4, :8], mesh_sharding(mesh22, "data", None))
        out_fp = np.asarray(
            make_generate_fn(CONFIG_TINY, mesh22, RULES_DP_TP, max_new_tokens=8)(
                params, prompt
            )
        )
        out_q = np.asarray(
            make_generate_fn(CFG_INT8, mesh22, RULES_DP_TP, max_new_tokens=8)(
                params, prompt
            )
        )
        np.testing.assert_array_equal(out_q[:, :8], out_fp[:, :8])
        # ≤0.4% per-element cache error: the first tokens should agree on
        # (at least) most rows; full-sequence divergence is allowed.
        assert (out_q[:, 8] == out_fp[:, 8]).mean() >= 0.75

    def test_decode_logits_stay_close(self, mesh22, trained):
        """Teacher-forcing through the int8 cache: logits near the fp-cache
        logits at every position (the cache is the only difference)."""
        params, tokens = trained
        seq = jnp.asarray(tokens[:2, :16])

        def forced_logits(cfg):
            model = Transformer(dataclasses.replace(cfg, decode=True))
            with activate(mesh22, RULES_DP_TP):
                logits, variables = model.apply(
                    {"params": params}, seq[:, :1], mutable=("cache",)
                )
                outs = [logits]
                for i in range(1, seq.shape[1]):
                    logits, variables = model.apply(
                        {"params": params, **variables}, seq[:, i : i + 1],
                        mutable=("cache",),
                    )
                    outs.append(logits)
            return np.concatenate([np.asarray(o, np.float32) for o in outs], axis=1)

        lp = forced_logits(CONFIG_TINY)
        lq = forced_logits(CFG_INT8)
        # Same argmax nearly everywhere, small absolute drift.
        agree = (lp.argmax(-1) == lq.argmax(-1)).mean()
        assert agree >= 0.9, agree
        assert np.abs(lp - lq).mean() < 0.05 * np.abs(lp).mean() + 0.05

    def test_beam_search_gathers_quantized_cache(self, mesh22, trained):
        params, tokens = trained
        prompt = put(tokens[:4, :8], mesh_sharding(mesh22, "data", None))
        beam = make_beam_search_fn(
            CFG_INT8, mesh22, RULES_DP_TP, beam_size=3, max_new_tokens=6,
        )
        out, scores = beam(params, prompt)
        assert np.asarray(out).shape == (4, 14)
        assert np.isfinite(np.asarray(scores)).all()

    def test_plain_storage_cast_path(self, mesh22, trained):
        """kv_cache_dtype=bf16 under fp32 compute: a plain storage cast."""
        params, tokens = trained
        prompt = put(tokens[:4, :8], mesh_sharding(mesh22, "data", None))
        cfg = dataclasses.replace(CONFIG_TINY, kv_cache_dtype=jnp.bfloat16)
        out = np.asarray(
            make_generate_fn(cfg, mesh22, RULES_DP_TP, max_new_tokens=6)(
                params, prompt
            )
        )
        assert out.shape == (4, 14)
        assert ((0 <= out) & (out < CONFIG_TINY.vocab_size)).all()
