"""Async-dispatch timing audit over cases/ (round-6 satellite).

The reference times a jitted loop with neither warmup nor a sync point
(`/root/reference/case6_attention.py:234-238`), so it measures dispatch,
not execution. Audit result for our cases/: every timing site routes
through ``utils.bench.measure``/``time_fn`` (warmup + host-readback
sync) or syncs via a host readback (``float(loss)``, ``np.asarray``);
no case touches a raw wall clock. This test is the tripwire that keeps
it that way: a case that starts timing with ``time.perf_counter`` /
``time.time`` must also contain an explicit honest-sync idiom, and no
case may ever time without one.
"""

import pathlib
import re

CASES = pathlib.Path(__file__).resolve().parents[1] / "cases"

RAW_CLOCKS = re.compile(
    r"time\.perf_counter\(|time\.time\(|time\.monotonic\(|timeit\."
)
#: The honest sync idioms: the bench harness (which owns warmup+sync),
#: an explicit readback, the tracer's sync point, or an engine call
#: (step/serve read results back to host before returning). ``float(``
#: is deliberately absent — ``float(dt)`` on the elapsed time itself
#: would satisfy a naive list while syncing nothing.
SYNC_IDIOMS = re.compile(
    r"measure\(|time_fn\(|block_until_ready|np\.asarray\(|"
    r"\.sync\(|device_sync\(|latency_stats\(|\.step\(|serve\("
)
#: A sync idiom must appear THIS close (in lines) to each raw clock
#: read — file-level matching would be vacuous, since nearly every case
#: calls np.asarray/float somewhere for unrelated reasons.
WINDOW = 10


def test_cases_never_time_raw_dispatch():
    assert CASES.is_dir()
    offenders = []
    for path in sorted(CASES.glob("*.py")):
        lines = path.read_text().splitlines()
        for i, line in enumerate(lines):
            if not RAW_CLOCKS.search(line):
                continue
            lo, hi = max(0, i - WINDOW), i + WINDOW + 1
            if not any(SYNC_IDIOMS.search(l) for l in lines[lo:hi]):
                offenders.append(f"{path.name}:{i + 1}")
    assert not offenders, (
        f"raw wall-clock reads with no sync point within ±{WINDOW} lines: "
        f"{offenders} — use utils.bench.measure/time_fn (warmup + "
        "host-readback sync) or read a result back before stopping the "
        "clock (the reference's flaw, case6_attention.py:234-238)"
    )


def test_case6_uses_the_corrected_harness():
    """The case rebuilt FROM the flawed reference loop must use the
    corrected harness explicitly (pinned so a refactor cannot silently
    regress it to a bare loop)."""
    text = (CASES / "case6_attention.py").read_text()
    assert "measure(" in text
    assert not RAW_CLOCKS.search(text)
