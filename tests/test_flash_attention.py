"""Flash attention kernel vs the dense reference op (Pallas interpret mode).

Runs the kernels through the Pallas interpreter on CPU — same kernel code the
TPU compiles, executed step-by-step — and checks numerics (forward AND
gradients) against ops.attention.dot_product_attention.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learning_jax_sharding_tpu.ops.attention import causal_mask, dot_product_attention
from learning_jax_sharding_tpu.ops.flash_attention import flash_attention

B, S, N, H = 2, 256, 2, 64


def _qkv(rng, s=S):
    shape = (B, s, N, H)
    return tuple(
        jnp.asarray(rng.standard_normal(shape).astype(np.float32)) for _ in range(3)
    )


def _flash(causal):
    return functools.partial(
        flash_attention, causal=causal, block_q=128, block_k=128, interpret=True
    )


class TestFlashForward:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, rng, causal):
        q, k, v = _qkv(rng)
        mask = causal_mask(S) if causal else None
        expected = dot_product_attention(q, k, v, mask=mask)
        got = _flash(causal)(q, k, v)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(expected), rtol=2e-4, atol=2e-5
        )

    def test_single_block(self, rng):
        q, k, v = _qkv(rng, s=128)
        expected = dot_product_attention(q, k, v)
        got = _flash(False)(q, k, v)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(expected), rtol=2e-4, atol=2e-5
        )

    def test_rejects_arbitrary_mask(self, rng):
        q, k, v = _qkv(rng, s=128)
        with pytest.raises(NotImplementedError):
            flash_attention(q, k, v, mask=causal_mask(128), interpret=True)

    def test_short_seq_shrinks_blocks(self, rng):
        # s < block: the wrapper clamps block sizes to the sequence length.
        q, k, v = _qkv(rng, s=96)
        got = flash_attention(q, k, v, block_q=128, block_k=128, interpret=True)
        expected = dot_product_attention(q, k, v)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(expected), rtol=2e-4, atol=2e-5
        )

    def test_rejects_indivisible_seq(self, rng):
        q, k, v = _qkv(rng, s=160)  # >block and not a block multiple
        with pytest.raises(ValueError):
            flash_attention(q, k, v, block_q=128, block_k=128, interpret=True)


class TestFlashBackward:
    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_dense(self, rng, causal):
        q, k, v = _qkv(rng)
        mask = causal_mask(S) if causal else None

        def dense_loss(q, k, v):
            out = dot_product_attention(q, k, v, mask=mask)
            return jnp.sum(out * jnp.cos(out))  # nontrivial cotangent

        def flash_loss(q, k, v):
            out = _flash(causal)(q, k, v)
            return jnp.sum(out * jnp.cos(out))

        dense_grads = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
        flash_grads = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
        for name, dg, fg in zip("qkv", dense_grads, flash_grads):
            np.testing.assert_allclose(
                np.asarray(fg), np.asarray(dg), rtol=5e-4, atol=5e-5,
                err_msg=f"d{name} mismatch",
            )


class TestGQANativeFlash:
    """GQA row folding: k/v enter at their native N_kv heads (group query
    heads fold into kernel q rows), so no repeat_kv expansion materializes
    and dk/dv reduce over the group inside the q-row sweep (VERDICT r1
    item 3)."""

    B, S, H = 2, 64, 16

    @pytest.mark.parametrize(
        "n_kv,group,causal,window",
        [
            (2, 3, True, None),   # GQA causal
            (4, 2, False, None),  # GQA bidirectional
            (2, 2, True, 16),     # GQA + sliding window (banded grid)
            (1, 4, True, None),   # MQA
        ],
    )
    def test_matches_dense_expanded(self, rng, n_kv, group, causal, window):
        from learning_jax_sharding_tpu.ops.attention import (
            causal_mask,
            dot_product_attention,
            sliding_window_mask,
        )

        n = n_kv * group
        q = jnp.asarray(rng.normal(size=(self.B, self.S, n, self.H)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(self.B, self.S, n_kv, self.H)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(self.B, self.S, n_kv, self.H)), jnp.float32)
        if window is not None:
            mask = sliding_window_mask(self.S, window)
        else:
            mask = causal_mask(self.S) if causal else None

        def expand(x):
            return jnp.repeat(x, group, axis=2)

        with jax.default_matmul_precision("float32"):
            out = flash_attention(
                q, k, v, causal=causal, window=window,
                block_q=16, block_k=16, interpret=True,
            )
            ref = dot_product_attention(q, expand(k), expand(v), mask=mask)
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)

            def loss_flash(q, k, v):
                return jnp.sum(
                    flash_attention(
                        q, k, v, causal=causal, window=window,
                        block_q=16, block_k=16, interpret=True,
                    ) ** 2
                )

            def loss_dense(q, k, v):
                return jnp.sum(
                    dot_product_attention(q, expand(k), expand(v), mask=mask) ** 2
                )

            gf = jax.grad(loss_flash, (0, 1, 2))(q, k, v)
            gd = jax.grad(loss_dense, (0, 1, 2))(q, k, v)
            for a, b in zip(gf, gd):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-3)

    def test_head_divisibility_rejected(self, rng):
        q = jnp.zeros((1, 8, 3, 8))
        k = jnp.zeros((1, 8, 2, 8))
        with pytest.raises(ValueError, match="not a multiple"):
            flash_attention(q, k, k, interpret=True)

    def test_model_skips_repeat_kv(self, rng):
        """MultiHeadAttention hands native-width k/v to supports_gqa
        backends; logits must match the dense GQA path."""
        import dataclasses

        from learning_jax_sharding_tpu.models.transformer import CONFIG_TINY, Transformer
        from learning_jax_sharding_tpu.ops.flash_attention import make_flash_attn_fn

        fn = make_flash_attn_fn(block_q=16, block_k=16, interpret=True)
        assert fn.supports_gqa
        base = dataclasses.replace(CONFIG_TINY, num_kv_heads=2)
        cfg_flash = dataclasses.replace(base, attn_fn=fn)
        tokens = jnp.asarray(
            np.random.default_rng(1).integers(0, base.vocab_size, (2, 32)),
            jnp.int32,
        )
        with jax.default_matmul_precision("float32"):
            params = Transformer(base).init({"params": jax.random.key(0)}, tokens)[
                "params"
            ]
            want = Transformer(base).apply({"params": params}, tokens)
            got = Transformer(cfg_flash).apply({"params": params}, tokens)
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want, np.float32), atol=2e-4
        )

    def test_shard_map_indivisible_kv_heads_fall_back(self, rng, mesh22):
        """HEADS→model axis that cannot divide N_kv: the mesh-aware wrapper
        expands k/v to full heads before shard_map (correctness over the
        native-width traffic win)."""
        from learning_jax_sharding_tpu.ops.attention import (
            causal_mask,
            dot_product_attention,
        )
        from learning_jax_sharding_tpu.ops.flash_attention import make_flash_attn_fn
        from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP

        n_kv, group, S, H = 3, 2, 32, 16     # 6 q heads ÷ 2 ok; 3 kv ÷ 2 not
        q = jnp.asarray(rng.normal(size=(2, S, n_kv * group, H)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(2, S, n_kv, H)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(2, S, n_kv, H)), jnp.float32)
        fn = make_flash_attn_fn(
            mesh22, RULES_DP_TP, block_q=16, block_k=16, interpret=True
        )
        with jax.default_matmul_precision("float32"):
            out = jax.jit(lambda a, b, c: fn(a, b, c, causal=True))(q, k, v)
            ref = dot_product_attention(
                q, jnp.repeat(k, group, axis=2), jnp.repeat(v, group, axis=2),
                mask=causal_mask(S),
            )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)
