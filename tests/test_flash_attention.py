"""Flash attention kernel vs the dense reference op (Pallas interpret mode).

Runs the kernels through the Pallas interpreter on CPU — same kernel code the
TPU compiles, executed step-by-step — and checks numerics (forward AND
gradients) against ops.attention.dot_product_attention.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learning_jax_sharding_tpu.ops.attention import causal_mask, dot_product_attention
from learning_jax_sharding_tpu.ops.flash_attention import flash_attention

B, S, N, H = 2, 256, 2, 64


def _qkv(rng, s=S):
    shape = (B, s, N, H)
    return tuple(
        jnp.asarray(rng.standard_normal(shape).astype(np.float32)) for _ in range(3)
    )


def _flash(causal):
    return functools.partial(
        flash_attention, causal=causal, block_q=128, block_k=128, interpret=True
    )


class TestFlashForward:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, rng, causal):
        q, k, v = _qkv(rng)
        mask = causal_mask(S) if causal else None
        expected = dot_product_attention(q, k, v, mask=mask)
        got = _flash(causal)(q, k, v)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(expected), rtol=2e-4, atol=2e-5
        )

    def test_single_block(self, rng):
        q, k, v = _qkv(rng, s=128)
        expected = dot_product_attention(q, k, v)
        got = _flash(False)(q, k, v)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(expected), rtol=2e-4, atol=2e-5
        )

    def test_rejects_arbitrary_mask(self, rng):
        q, k, v = _qkv(rng, s=128)
        with pytest.raises(NotImplementedError):
            flash_attention(q, k, v, mask=causal_mask(128), interpret=True)

    def test_short_seq_shrinks_blocks(self, rng):
        # s < block: the wrapper clamps block sizes to the sequence length.
        q, k, v = _qkv(rng, s=96)
        got = flash_attention(q, k, v, block_q=128, block_k=128, interpret=True)
        expected = dot_product_attention(q, k, v)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(expected), rtol=2e-4, atol=2e-5
        )

    def test_rejects_indivisible_seq(self, rng):
        q, k, v = _qkv(rng, s=160)  # >block and not a block multiple
        with pytest.raises(ValueError):
            flash_attention(q, k, v, block_q=128, block_k=128, interpret=True)


class TestFlashBackward:
    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_dense(self, rng, causal):
        q, k, v = _qkv(rng)
        mask = causal_mask(S) if causal else None

        def dense_loss(q, k, v):
            out = dot_product_attention(q, k, v, mask=mask)
            return jnp.sum(out * jnp.cos(out))  # nontrivial cotangent

        def flash_loss(q, k, v):
            out = _flash(causal)(q, k, v)
            return jnp.sum(out * jnp.cos(out))

        dense_grads = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
        flash_grads = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
        for name, dg, fg in zip("qkv", dense_grads, flash_grads):
            np.testing.assert_allclose(
                np.asarray(fg), np.asarray(dg), rtol=5e-4, atol=5e-5,
                err_msg=f"d{name} mismatch",
            )
