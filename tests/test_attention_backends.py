"""Swappable attention backends inside the model, under real meshes.

The same MultiHeadAttention must produce (numerically) the same function
whether its core is the dense einsum op, the Pallas flash kernel (via
shard_map, interpret mode on CPU), or ring attention over a sequence-sharded
mesh — backend choice is a deployment decision, not a model change.
"""

import jax
import jax.numpy as jnp
import numpy as np

from learning_jax_sharding_tpu.models.attention import MultiHeadAttention
from learning_jax_sharding_tpu.ops.flash_attention import make_flash_attn_fn
from learning_jax_sharding_tpu.ops.ring_attention import make_ring_attn_fn
from learning_jax_sharding_tpu.parallel import put, shard_shapes
from learning_jax_sharding_tpu.parallel.logical import (
    BATCH,
    EMBED,
    RULES_DP_SP,
    RULES_DP_TP,
    SEQ,
    activate,
    logical_sharding,
)

B, S, M = 4, 128, 64
HEADS_N, HEAD_DIM = 4, 16


def _model(attn_fn=None, causal=False):
    return MultiHeadAttention(
        features=M, num_heads=HEADS_N, head_dim=HEAD_DIM,
        causal=causal, attn_fn=attn_fn,
    )


def _data(rng):
    return jnp.asarray(rng.standard_normal((B, S, M)).astype(np.float32))


class TestBackendEquivalence:
    def test_flash_matches_dense_in_model(self, mesh22, rng):
        """Flash backend under shard_map (batch over data, heads over model)
        vs the dense backend, same params, inside jit on the mesh."""
        x = put(_data(rng), logical_sharding(mesh22, RULES_DP_TP, BATCH, SEQ, EMBED))
        dense = _model(causal=True)
        flash = _model(
            attn_fn=make_flash_attn_fn(
                mesh=mesh22, rules=RULES_DP_TP, interpret=True, block_q=64, block_k=64
            ),
            causal=True,
        )
        with activate(mesh22, RULES_DP_TP):
            params = dense.init({"params": jax.random.key(0)}, x)["params"]
            y_dense = jax.jit(lambda p, x: dense.apply({"params": p}, x))(params, x)
            y_flash = jax.jit(lambda p, x: flash.apply({"params": p}, x))(params, x)
        np.testing.assert_allclose(
            np.asarray(y_flash), np.asarray(y_dense), rtol=2e-4, atol=2e-5
        )

    def test_ring_matches_dense_in_model(self, mesh22, rng):
        """Ring backend with the sequence sharded over 'model'
        (RULES_DP_SP) vs the dense backend."""
        x = put(_data(rng), logical_sharding(mesh22, RULES_DP_SP, BATCH, SEQ, EMBED))
        dense = _model(causal=True)
        ring = _model(attn_fn=make_ring_attn_fn(mesh22, RULES_DP_SP), causal=True)
        with activate(mesh22, RULES_DP_SP):
            params = dense.init({"params": jax.random.key(0)}, x)["params"]
            y_dense = jax.jit(lambda p, x: dense.apply({"params": p}, x))(params, x)
            y_ring = jax.jit(lambda p, x: ring.apply({"params": p}, x))(params, x)
        np.testing.assert_allclose(
            np.asarray(y_ring), np.asarray(y_dense), rtol=2e-4, atol=2e-5
        )
        # And the ring output keeps the sequence dim sharded (GSPMD is free to
        # choose the batch placement absent an out_sharding on this jit).
        assert shard_shapes(y_ring)[0][1] == S // 2
