"""MFU ablation 3: mixed-precision param/master/moment stacks, scan x8."""
import dataclasses
import numpy as np, jax, jax.numpy as jnp, optax
from jax import lax
from learning_jax_sharding_tpu.models.transformer import (
    CONFIG_125M, Transformer, fused_next_token_loss)
from learning_jax_sharding_tpu.ops.flash_attention import make_flash_attn_fn
from learning_jax_sharding_tpu.parallel import build_mesh, mesh_sharding, put
from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP, activate
from learning_jax_sharding_tpu.training.pipeline import sharded_train_state
from learning_jax_sharding_tpu.training.precision import master_weights
from learning_jax_sharding_tpu.utils.bench import time_fn

mesh = build_mesh((1, 1), ("data", "model"))
b, s = 8, 1024
rng = np.random.default_rng(0)
tokens = rng.integers(0, 50304, size=(b, s + 1)).astype(np.int32)
sh = mesh_sharding(mesh, "data", None)
batch = {"inputs": put(tokens[:, :-1], sh), "targets": put(tokens[:, 1:], sh)}

def bench_cfg(cfg, opt, tag, k=8):
    model = Transformer(cfg)
    FLOPS = cfg.train_step_flops(b, s)
    def loss_of(params, bt):
        hidden = model.apply({"params": params}, bt["inputs"], return_hidden=True)
        return fused_next_token_loss(hidden, bt, params)
    state, _ = sharded_train_state(
        model, opt, batch["inputs"], {"params": jax.random.key(0)}, mesh, RULES_DP_TP)
    def body(st, _):
        grads = jax.grad(lambda p: loss_of(p, batch))(st.params)
        return st.apply_gradients(grads=grads), None
    def many(st):
        st, _ = lax.scan(body, st, None, length=k)
        return st
    with activate(mesh, RULES_DP_TP):
        secs = time_fn(jax.jit(many), state, min_time=2.0) / k
    print(f"{tag}: {secs*1e3:.2f} ms/step, {FLOPS/secs/1e12:.1f} TFLOP/s, MFU={FLOPS/secs/197e12:.1%}", flush=True)
    del state

CFG = dataclasses.replace(CONFIG_125M, attn_fn=make_flash_attn_fn())
CFG_BF16P = dataclasses.replace(CFG, param_dtype=jnp.bfloat16)

bench_cfg(CFG_BF16P, master_weights(optax.adamw(3e-4)),
          "bf16 params + fp32 master, fp32 moments")
bench_cfg(CFG_BF16P, master_weights(optax.adamw(3e-4, mu_dtype=jnp.bfloat16)),
          "bf16 params + fp32 master, mu=bf16")
bench_cfg(CFG, optax.adamw(3e-4, mu_dtype=jnp.bfloat16),
          "fp32 params, mu=bf16 (yesterday's best, rerun)")
